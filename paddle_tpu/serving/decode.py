"""Batched autoregressive decode through the serving engine.

The decode workload is the serving engine's hardest shape-discipline
test: every request carries its own prompt length AND runs two phases
(prefill over the prompt, then a scanned per-token decode), so a naive
server compiles per (batch, prompt-length, generation-length) triple —
under real traffic, forever.  The bucketed answer mirrors the dense
path's ladder, squared:

  * request ROWS pack into the batch-bucket ladder exactly like dense
    requests (scheduler.py's continuous batcher is reused unchanged);
  * prompt LENGTHS pad (left) to the FLAGS_decode_buckets sequence
    ladder; the KV-cache length rounds up to the smallest bucket holding
    prompt-bucket + max_new_tokens;
  * warm-up AOT-compiles every (batch-bucket × prefill-bucket) prefill
    executable and every (batch-bucket × cache-bucket) decode executable
    through text.generation.Generator, each ledgered at the model's
    ``serving:<name>`` site — so ``assert_zero_steady_state_recompiles``
    covers mixed prefill/decode traffic with no special casing.

Left-padding makes results batch-invariant: a row's attention window is
``[P - len, pos)`` regardless of which rows share its batch, so a served
greedy decode is bit-identical to a batch-1 ``generate()`` of the same
prompt (the admission test's oracle).

``FLAGS_decode_slots > 0`` swaps the scanned run-to-completion loop for
the iteration-level slot loop (serving/slots.py): ONE single-step
executable per (slot-count, cache-bucket), requests joining and
retiring at token boundaries, prompts chunked ``FLAGS_prefill_chunk``
wide and interleaved into decode steps.  Tokens stay bit-identical to
``generate()``; only the schedule changes.  The flag off (default) is
one Python branch at load — the scanned path is byte-identical to
before.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

import numpy as np

from ..framework import flags as _flags
from ..framework.enforce import (InvalidArgumentError, OutOfRangeError,
                                 PreconditionNotMetError)
from ..profiler import tracing as _tracing
from ..profiler.metrics import LatencyWindow, RateMeter
from ..utils.monitor import stat_add
from .bucketing import BucketLadder

__all__ = ["DecodeModelSpec", "DecodeRequest"]


@dataclass
class DecodeModelSpec:
    """One served decode model: a LIVE layer implementing the
    init_cache/forward_cached contract (text.models.GPTModel), not a
    frozen export — the decode program (a scanned step over a mutable
    ring cache) is compiled per bucket at warm-up, which is exactly the
    durable artifact the dense path gets from export_for_serving.

    ``draft_layer`` turns the spec into a draft/target PAIR: under
    ``FLAGS_spec_decode`` the runtime serves through speculative
    decoding (text/speculative.py — the draft proposes ``gamma`` tokens
    per step, the target verifies them in one forward; served tokens
    stay bit-identical to plain greedy decode), and the warm-up grid
    AOT-compiles the speculative step per (batch-bucket × cache-bucket)
    so ``assert_zero_steady_state_recompiles`` holds under mixed
    traffic exactly as before.  With the flag off (the default) the
    draft is ignored — one Python branch at load."""

    name: str
    layer: Any
    batch_buckets: Optional[Sequence[int]] = None
    seq_buckets: Optional[Sequence[int]] = None
    max_new_tokens: int = 16
    max_len: Optional[int] = None
    eos_token_id: Optional[int] = None
    draft_layer: Any = None
    gamma: Optional[int] = None
    # sharded replicas (serving/cluster/sharding.py): AOT-compile the
    # grids SPMD over ``mesh`` with params sharded by the autoshard
    # rules table (``rules`` = a PartitionRules / table name; None =
    # the active table).  mesh=None is the single-device path.
    mesh: Any = None
    rules: Any = None


@dataclass
class DecodeRequest:
    """One client decode request: ``rows`` prompts (variable lengths),
    each to be continued by up to ``max_new`` tokens."""

    model: str
    prompts: List[np.ndarray]
    rows: int
    max_new: int
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=time.perf_counter)
    # root span (Server.submit_decode) + monotonic enqueue stamp — the
    # same tracing contract as the dense Request
    trace: Optional[object] = None
    t_enqueue_mono: float = field(default_factory=time.monotonic)
    # admission class (scheduler.RequestQueue): same contract as Request
    tenant: str = "default"
    priority: Optional[int] = None
    # conversation identity (FLAGS_session_store): single-prompt requests
    # only — the slot loop parks/restores the KV planes under this key
    session_id: Optional[str] = None


class _DecodeRuntime:
    """Serving-side runtime for one decode model (the decode analogue of
    server._ModelRuntime): Generator-backed executables, bucket plans,
    metrics, and the strict steady-state discipline."""

    kind = "decode"
    backend = "decode"
    primary = None                      # no Predictor to clone

    def __init__(self, spec: DecodeModelSpec):
        self.spec = spec
        self.name = spec.name
        self.site = f"serving:{spec.name}"
        self.ladder = BucketLadder.from_flag(
            spec.batch_buckets if spec.batch_buckets is not None
            else _flags.flag("serving_buckets"))
        self.steps = int(spec.max_new_tokens)
        self.admitted = False
        self.gen = None
        self.role = "both"              # resolved from the flag at load()
        self._loop = None               # slot mode, resolved at load()
        self.slots = 0
        self._warmed_prefill = set()        # {(B, P, C)}
        self._warmed_decode = set()         # {(B, C)}
        self.latency = LatencyWindow(
            int(_flags.flag("serving_metrics_window")))
        self.rate = RateMeter()
        self._mlock = threading.Lock()
        # injected by the Server before warmup (FLAGS_session_store);
        # the prefix cache is built per-runtime in _warmup_slots
        self.session_store = None
        self.prefix_cache = None
        self.counters = {"requests": 0, "completed": 0,  # guarded-by: _mlock
                         "errors": 0,
                         "batches": 0, "rows": 0, "padded_rows": 0,
                         "steady_compiles": 0}

    def bump(self, **kw):
        with self._mlock:
            for k, v in kw.items():
                self.counters[k] += v

    # -- loading + warm-up ---------------------------------------------------
    def load(self):
        from ..text.generation import Generator
        # pool role (FLAGS_serving_role): a prefill-pool replica warms
        # and serves only the prefill grid, a decode-pool replica only
        # the decode grid (full submit_decode traffic needs "both");
        # resolved at load so one process = one role, like one mesh
        self.role = str(_flags.flag("serving_role")).lower()
        if self.spec.mesh is not None:
            if self.spec.draft_layer is not None \
                    and bool(_flags.flag("spec_decode")):
                raise PreconditionNotMetError(
                    f"decode model {self.name!r}: speculative decoding "
                    "and a sharded mesh cannot combine (the draft runs "
                    "per-replica unsharded) — drop one")
            from .cluster.sharding import serving_shard_specs
            specs = serving_shard_specs(self.spec.layer, self.spec.mesh,
                                        self.spec.rules)
            self.gen = Generator(self.spec.layer, site=self.site,
                                 seq_buckets=self.spec.seq_buckets,
                                 max_len=self.spec.max_len,
                                 mesh=self.spec.mesh, param_specs=specs)
        elif self.spec.draft_layer is not None \
                and bool(_flags.flag("spec_decode")):
            from ..text.speculative import SpeculativeGenerator
            self.gen = SpeculativeGenerator(
                self.spec.layer, self.spec.draft_layer, site=self.site,
                seq_buckets=self.spec.seq_buckets,
                max_len=self.spec.max_len, gamma=self.spec.gamma)
        else:
            self.gen = Generator(self.spec.layer, site=self.site,
                                 seq_buckets=self.spec.seq_buckets,
                                 max_len=self.spec.max_len)
        # every prompt bucket must leave room for max_new_tokens in some
        # cache bucket — refuse at registration time, not under traffic
        self._plan = []
        for p in self.gen.seq_buckets:
            try:
                c = self.gen.cache_bucket(p, self.steps)
            except OutOfRangeError:
                continue                # prompts this long are rejected
            self._plan.append((p, c))
        if not self._plan:
            raise PreconditionNotMetError(
                f"decode model {self.name!r}: no sequence bucket leaves "
                f"room for max_new_tokens={self.steps} under "
                f"max_len={self.gen._max_len}")
        self.max_prompt = max(p for p, _ in self._plan)
        # iteration-level slot mode (FLAGS_decode_slots): one step loop
        # at the LARGEST cache bucket replaces the scanned grid; prompts
        # chunk to FLAGS_prefill_chunk instead of prefill-bucketing
        self._loop = None
        self.slots = int(_flags.flag("decode_slots"))
        self.chunk_width = int(_flags.flag("prefill_chunk"))
        if self.slots:
            if self.spec.mesh is not None:
                raise PreconditionNotMetError(
                    f"decode model {self.name!r}: the slot loop "
                    "(FLAGS_decode_slots) runs per-replica unsharded — "
                    "drop the mesh or set FLAGS_decode_slots=0")
            if self.role != "both":
                raise PreconditionNotMetError(
                    f"decode model {self.name!r}: the slot loop fuses "
                    "chunked prefill into the decode step, so it cannot "
                    f"serve a disaggregated {self.role!r} pool — use "
                    "FLAGS_serving_role=both or FLAGS_decode_slots=0")
            self._slot_cache = max(c for _, c in self._plan)
            gamma = int(getattr(self.gen, "_gamma", 0)) \
                if getattr(self.gen, "_draft", None) is not None else 0
            span = self._slot_cache - self.steps - gamma
            T = self.chunk_width
            # largest admissible prompt: its chunk-padded span plus the
            # full token budget must fit ONE ring session
            self.max_prompt = (span // T) * T
            if self.max_prompt < 1:
                raise PreconditionNotMetError(
                    f"decode model {self.name!r}: slot cache "
                    f"{self._slot_cache} leaves no room for a prompt "
                    f"chunk (chunk={T}, max_new_tokens={self.steps}, "
                    f"gamma={gamma})")

    def lint_gate(self, B, P, C):
        """Graph-lint admission over the prefill program in abstract-eval
        mode (the dense runtimes' gate, FLAGS_graph_lint): ERROR findings
        refuse admission.  The ring-cache dynamic_update_slice writes are
        exactly what the layout pass's KV exemption covers."""
        from .. import analysis
        if not analysis.lint_enabled():
            return
        import jax
        import jax.numpy as jnp
        fn = self.gen._build_prefill(B, P, C)
        try:
            closed = jax.make_jaxpr(fn)(
                *self.gen._state_avals(),
                jax.ShapeDtypeStruct((B, P), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.int32))
        except Exception as e:   # noqa: BLE001 — lint must not mask bugs
            import warnings
            warnings.warn(
                f"decode warm-up lint for {self.name!r} b{B} p{P} could "
                f"not abstract-eval the program: {type(e).__name__}: {e}",
                analysis.GraphLintWarning, stacklevel=2)
            return
        ctx = analysis.LintContext(site=self.site, kind="serving",
                                   closed_jaxpr=closed)
        report = analysis.default_pass_manager().run(ctx)
        analysis.emit(report, mode="warn")
        errors = report.by_severity(analysis.Severity.ERROR)
        if errors:
            raise PreconditionNotMetError(
                f"serving refused to admit decode model {self.name!r}: "
                f"graph lint found {len(errors)} ERROR finding(s) at "
                f"(batch={B}, prompt={P}):\n"
                + "\n".join("  " + str(d) for d in errors))

    def lint_gate_slot(self, S, C):
        """Graph-lint admission over the slot STEP program — the slot
        loop's hot path gets the same abstract-eval gate as the scanned
        grid (ERROR findings refuse admission)."""
        from .. import analysis
        if not analysis.lint_enabled():
            return
        import jax
        eos = self.spec.eos_token_id
        end = -1 if eos is None else int(eos)
        fn = self.gen._build_step(S, C, end)
        try:
            closed = jax.make_jaxpr(fn)(*self.gen._state_avals(),
                                        *self.gen.step_avals(S, C))
        except Exception as e:   # noqa: BLE001 — lint must not mask bugs
            import warnings
            warnings.warn(
                f"decode warm-up lint for {self.name!r} slots {S} could "
                f"not abstract-eval the step program: "
                f"{type(e).__name__}: {e}",
                analysis.GraphLintWarning, stacklevel=2)
            return
        ctx = analysis.LintContext(site=self.site, kind="serving",
                                   closed_jaxpr=closed)
        report = analysis.default_pass_manager().run(ctx)
        analysis.emit(report, mode="warn")
        errors = report.by_severity(analysis.Severity.ERROR)
        if errors:
            raise PreconditionNotMetError(
                f"serving refused to admit decode model {self.name!r}: "
                f"graph lint found {len(errors)} ERROR finding(s) in "
                f"the slot step program (slots={S}, cache={C}):\n"
                + "\n".join("  " + str(d) for d in errors))

    def _warmup_slots(self):
        """Slot-mode warm-up: lint-gate + AOT-compile the step and chunk
        executables (persistent cache + ledger, like every grid point),
        build the SlotLoop, run one dummy request end-to-end so every
        dispatch path is warm, then zero the loop accounting."""
        from .slots import SlotLoop
        S, C, T = self.slots, self._slot_cache, self.chunk_width
        self.lint_gate_slot(S, C)
        eos = self.spec.eos_token_id
        self._audit_gate(self.gen.step_exec(S, C, eos), S, None)
        self._audit_gate(self.gen.chunk_exec(S, T, C), S, None)
        if bool(_flags.flag("prefix_cache")):
            import jax.tree_util as tu
            from .cluster.handoff import _np_dtype
            from .prefix_cache import PrefixCache
            block_nbytes = sum(
                int(np.prod(tuple(a.shape)))
                * _np_dtype(str(a.dtype)).itemsize
                for a in tu.tree_leaves(self.gen._block_avals(S, T, C)))
            self.prefix_cache = PrefixCache(
                T, block_nbytes,
                hbm_budget_mb=float(_flags.flag("prefix_cache_hbm_mb")))
        self._loop = SlotLoop(self.gen, S, C, T, eos_token_id=eos,
                              model=self.name,
                              prefix_cache=self.prefix_cache,
                              session_store=self.session_store)
        self._loop.submit(np.zeros((1,), np.int32), 1).result(timeout=600)
        self._loop.reset_stats()
        if self.prefix_cache is not None:
            self.prefix_cache.clear()   # drop the warm-up dummy's blocks
        self.admitted = True

    def warmup(self):
        """AOT-compile the (batch-bucket × prefill-bucket) prefill set
        and/or the (batch-bucket × cache-bucket) decode set — the pool
        role decides which (a prefill-pool replica never compiles the
        decode grid and vice versa; "both" compiles everything) — then
        run each warmed phase once on zeros so dispatch paths are warm
        too.  Every compile lands in the ledger at this runtime's site —
        the steady-state mark the server snapshots right after.  Under
        ``spec.mesh`` the grids compile SPMD and each executable is
        HLO-audited at admission (cluster/sharding.py)."""
        import jax
        if self._loop is not None or self.slots:
            self._warmup_slots()
            return
        eos = self.spec.eos_token_id
        warm_prefill = self.role in ("both", "prefill")
        warm_decode = self.role in ("both", "decode")
        for B in self.ladder:
            linted = set()
            for P, C in self._plan:
                if warm_prefill:
                    if P not in linted:
                        self.lint_gate(B, P, C)
                        linted.add(P)
                    ex = self.gen.prefill_exec(B, P, C)
                    self._audit_gate(ex, B, P)
                    self._warmed_prefill.add((B, P, C))
                if warm_decode and (B, C) not in self._warmed_decode:
                    ex = self.gen.decode_exec(B, C, self.steps, 1, eos)
                    self._audit_gate(ex, B, None)
                    self._warmed_decode.add((B, C))
            # one zeros round-trip per batch bucket: warm dispatch/runtime
            # for exactly the phases this pool owns
            P0, C0 = self._plan[0]
            ids = np.zeros((B, P0), np.int32)
            start = np.full((B,), P0 - 1, np.int32)
            if warm_prefill:
                cache, logits0 = self.gen.prefill(ids, start, C0)
                if warm_decode:
                    toks = self.gen.decode(cache, logits0, start, P0,
                                           self.steps, 1, eos)
                    jax.block_until_ready(toks)
                else:
                    jax.block_until_ready(logits0)
            else:
                cache = self._zero_cache(B, C0)
                logits0 = np.zeros((B, self.gen._vocab_size()),
                                   np.float32)
                toks = self.gen.decode(cache, logits0, start, P0,
                                       self.steps, 1, eos)
                jax.block_until_ready(toks)
        self.admitted = True

    def _audit_gate(self, compiled, B, P):
        """Admission HLO audit of one warmed grid executable (sharded
        replicas only; FLAGS_hlo_audit-gated — off-path = one branch)."""
        if self.spec.mesh is None:
            return
        from .cluster.sharding import shard_admission_audit
        shard_admission_audit(
            compiled, site=self.site, mesh=self.spec.mesh,
            param_specs=self.gen._param_specs,
            mesh_label=self.gen._mesh_label())

    def _zero_cache(self, B, C):
        """An all-zeros ring cache at the warmed layout — the decode-only
        pool's warm-dispatch stand-in for a prefill it will never run."""
        import jax
        from .cluster.handoff import _np_dtype
        shapes = jax.eval_shape(lambda: self.gen._init_cache_raw(B, C))
        out = []
        for c in shapes:
            planes = []
            for p in c:
                z = np.zeros(tuple(p.shape), _np_dtype(str(p.dtype)))
                planes.append(jax.device_put(
                    z, self.gen.kv_plane_sharding(tuple(p.shape))))
            out.append(tuple(planes))
        return out

    # -- traffic -------------------------------------------------------------
    def validate(self, prompts, max_new):
        if not prompts:
            raise InvalidArgumentError("empty decode request (0 prompts)")
        out = []
        for i, p in enumerate(prompts):
            a = np.asarray(p)
            if a.ndim != 1 or a.size == 0 \
                    or not np.issubdtype(a.dtype, np.integer):
                raise InvalidArgumentError(
                    f"decode prompt {i} must be a non-empty 1-D int "
                    f"array, got shape {a.shape} dtype {a.dtype}")
            if a.size > self.max_prompt:
                raise OutOfRangeError(
                    f"decode prompt {i} has {a.size} tokens; the largest "
                    f"admissible prompt bucket is {self.max_prompt} "
                    f"(max_new_tokens={self.steps}, ladder "
                    f"{self.gen.seq_buckets})")
            out.append(a.astype(np.int32))
        mn = self.steps if max_new is None else int(max_new)
        if mn < 1 or mn > self.steps:
            raise InvalidArgumentError(
                f"max_new_tokens must be in [1, {self.steps}] "
                f"(the engine's warmed decode length), got {mn}")
        return out, mn

    def execute(self, batch):
        """Run one packed batch through prefill + scanned decode; returns
        generated tokens [bucket, steps] (padding rows included — the
        worker slices per request).  In slot mode the rows go through
        the iteration-level loop instead: each row is its own slot
        tenancy (joins at a token boundary, retires when done), and the
        worker-facing [bucket, steps] contract is assembled from the
        per-row futures — workers and the scheduler don't change."""
        if self._loop is not None:
            futs = []
            for r in batch.requests:
                sid = getattr(r, "session_id", None)
                snap = None
                if sid is not None and self.session_store is not None:
                    snap = self.session_store.take(sid)
                    if snap is not None and snap.model != self.name:
                        # a stale key collision across models: put the
                        # snapshot back untouched and prefill plainly
                        self.session_store.put(snap)
                        snap = None
                for p in r.prompts:
                    try:
                        futs.append(self._loop.submit(
                            p, r.max_new, session_id=sid, snapshot=snap))
                    except (InvalidArgumentError, OutOfRangeError):
                        # a malformed snapshot must not fail the turn —
                        # fall back to the plain (bit-identical) prefill
                        futs.append(self._loop.submit(
                            p, r.max_new, session_id=sid))
                    snap = None             # one snapshot, one restore
            out = np.zeros((batch.bucket, self.steps), np.int32)
            row = 0
            for r in batch.requests:
                err = None
                for _ in range(len(r.prompts)):
                    try:
                        got = futs[row].result(timeout=600)
                        out[row, :got.size] = got
                    except Exception as e:   # noqa: BLE001 — per-request
                        err = e              # isolation: a parked row's
                    row += 1                 # Unavailable must not fail
                if err is not None:          # its batch-mates
                    if not r.future.done():
                        r.future.set_exception(err)
            return out
        prompts = [p for r in batch.requests for p in r.prompts]
        # pad rows up to the batch bucket with 1-token dummy prompts
        prompts += [np.zeros((1,), np.int32)] * (batch.bucket - batch.rows)
        P = self.gen.prefill_bucket(max(p.size for p in prompts))
        C = self.gen.cache_bucket(P, self.steps)
        B = batch.bucket
        key_missing = ((B, P, C) not in self._warmed_prefill
                       or (B, C) not in self._warmed_decode)
        if key_missing:
            if bool(_flags.flag("serving_strict")):
                raise PreconditionNotMetError(
                    f"decode model {self.name!r}: (batch={B}, prompt="
                    f"{P}, cache={C}) has no warm-up executable "
                    "(FLAGS_serving_strict=True refuses steady-state "
                    "compiles — extend the ladders and re-warm)")
            # escape hatch: Generator ledgers the compile at this site,
            # so the zero-recompile invariant visibly fails
            stat_add("serving_steady_compiles")
            self.bump(steady_compiles=1)
        ids, start = self.gen.pack_prompts(prompts, P)
        traced = [r for r in batch.requests
                  if getattr(r, "trace", None) is not None]
        if not traced:                     # off-path: one branch, no fence
            cache, logits0 = self.gen.prefill(ids, start, C)
            toks = self.gen.decode(cache, logits0, start, P, self.steps,
                                   1, self.spec.eos_token_id)
            out = np.asarray(toks)
        else:
            import jax
            t_p0 = time.monotonic()
            cache, logits0 = self.gen.prefill(ids, start, C)
            # fence so the prefill/decode split is honest device time
            # (only traced batches pay this extra sync point)
            jax.block_until_ready(logits0)
            t_p1 = time.monotonic()
            toks = self.gen.decode(cache, logits0, start, P, self.steps,
                                   1, self.spec.eos_token_id)
            out = np.asarray(toks)         # fences the scanned token loop
            t_d1 = time.monotonic()
            dt = (t_d1 - t_p1) / self.steps
            spec = getattr(self.gen, "last_stats", None)
            for r in traced:
                _tracing.child(r.trace, "prefill", t_p0, t_p1,
                               prompt_bucket=P, cache_bucket=C, batch=B)
                d = _tracing.start_span("decode", parent=r.trace,
                                        t0=t_p1, steps=self.steps,
                                        cache_bucket=C, batch=B,
                                        per_token_ms=round(dt * 1e3, 4))
                if d is not None:
                    if spec:
                        # speculative runtime: estimated draft/verify
                        # children (the scan is one device program; the
                        # parameter-byte ratio splits the window) plus
                        # the measured acceptance stats
                        tm = t_p1 + (t_d1 - t_p1) * spec["draft_fraction"]
                        _tracing.child(d, "draft", t_p1, tm,
                                       estimated=True,
                                       gamma=spec["gamma"],
                                       proposed=spec["proposed"],
                                       spec_steps=spec["spec_steps"])
                        _tracing.child(d, "verify", tm, t_d1,
                                       estimated=True,
                                       accepted=spec["accepted"],
                                       acceptance_rate=spec[
                                           "acceptance_rate"])
                        d.set_attr(gamma=spec["gamma"], acceptance_rate=
                                   spec["acceptance_rate"])
                    # per-token events, attributed at the scan boundary:
                    # the whole token loop is ONE jitted lax.scan (one
                    # device program), so the host never observes token k
                    # alone — timestamps spread uniformly across the
                    # fenced scan window
                    for k in range(r.max_new):
                        d.event("token", t=t_p1 + (k + 1) * dt, index=k)
                    _tracing.finish(d, end=t_d1)
        if key_missing:
            self._warmed_prefill.add((B, P, C))
            self._warmed_decode.add((B, C))
        return out

    # -- disaggregated pools: explicit prefill → handoff → decode ------------
    def _steady_guard(self, warmed, key, what):
        if key in warmed:
            return False
        if bool(_flags.flag("serving_strict")):
            raise PreconditionNotMetError(
                f"decode model {self.name!r}: {what} {key} has no "
                "warm-up executable (FLAGS_serving_strict=True refuses "
                "steady-state compiles — extend the ladders and re-warm)")
        stat_add("serving_steady_compiles")
        self.bump(steady_compiles=1)
        return True

    def prefill_handoff(self, prompts, max_new_tokens=None):
        """Run ONLY the prefill phase over ``prompts`` and return the
        :class:`~.cluster.handoff.KVHandoff` a decode pool resumes from:
        device-resident ring planes (bf16 or int8+scales), next-token
        logits, per-row validity offsets and the cache_position.  The
        prefill-pool entry point (roles "both"/"prefill")."""
        if self._loop is not None:
            raise PreconditionNotMetError(
                f"decode model {self.name!r}: disaggregated KV handoff "
                "rides the scanned run-to-completion path — set "
                "FLAGS_decode_slots=0 to serve a prefill pool")
        if self.role == "decode":
            raise PreconditionNotMetError(
                f"decode model {self.name!r}: this replica is in the "
                "decode pool (FLAGS_serving_role=decode) — prefill "
                "belongs to the prefill pool")
        from .cluster.handoff import KVHandoff
        arrs, mn = self.validate(list(prompts), max_new_tokens)
        rows = len(arrs)
        B = self.ladder.bucket_for(rows)
        padded = arrs + [np.zeros((1,), np.int32)] * (B - rows)
        P = self.gen.prefill_bucket(max(p.size for p in padded))
        C = self.gen.cache_bucket(P, self.steps)
        missed = self._steady_guard(self._warmed_prefill, (B, P, C),
                                    "prefill grid point")
        ids, start = self.gen.pack_prompts(padded, P)
        t0 = time.monotonic()
        cache, logits0 = self.gen.prefill(ids, start, C)
        h = KVHandoff(cache=cache, logits0=logits0,
                      start=np.asarray(start, np.int32), pos=P,
                      meta={"model": self.name, "rows": rows,
                            "max_new": mn, "batch": B,
                            "prompt_bucket": P, "cache_bucket": C,
                            "prefill_s": round(time.monotonic() - t0, 6)})
        if missed:
            self._warmed_prefill.add((B, P, C))
        return h

    def decode_from_handoff(self, handoff):
        """Resume a decode from a prefill pool's handoff: ingest the
        planes (device pass-through when already resident, device_put at
        the pinned KV layout when they arrived serialized), then run the
        scanned decode executable from the carried ``cache_position`` /
        validity window.  Returns generated ids [rows, max_new] — bit-
        identical to the same prompts run through the in-process
        ``generate()`` (the acceptance oracle).  The decode-pool entry
        point (roles "both"/"decode")."""
        if self._loop is not None:
            raise PreconditionNotMetError(
                f"decode model {self.name!r}: disaggregated KV handoff "
                "rides the scanned run-to-completion path — set "
                "FLAGS_decode_slots=0 to serve a decode pool")
        if self.role == "prefill":
            raise PreconditionNotMetError(
                f"decode model {self.name!r}: this replica is in the "
                "prefill pool (FLAGS_serving_role=prefill) — decode "
                "belongs to the decode pool")
        cache = handoff.cache
        if not cache:
            raise InvalidArgumentError("empty KV handoff (no planes)")
        if isinstance(cache[0][0], np.ndarray):
            handoff = handoff.device(self.gen.kv_plane_sharding)
            cache = handoff.cache
        B = int(np.shape(handoff.logits0)[0])
        C = int(np.shape(cache[0][0])[2])
        missed = self._steady_guard(self._warmed_decode, (B, C),
                                    "decode grid point")
        toks = self.gen.decode(cache, handoff.logits0, handoff.start,
                               int(handoff.pos), self.steps, 1,
                               self.spec.eos_token_id)
        out = np.asarray(toks)
        if missed:
            self._warmed_decode.add((B, C))
        rows = int(handoff.meta.get("rows", B))
        mn = int(handoff.meta.get("max_new", self.steps))
        return out[:rows, :mn]

    def slot_signals(self):
        """Token-level slot accounting for Server.signals(), or None on
        the scanned path (the ClusterSignals leg is additive)."""
        return None if self._loop is None else self._loop.signals()

    def close(self):
        if self._loop is not None:
            self._loop.close()

    def publish(self):
        self.latency.publish(f"serving_{self.name}")
        self.rate.publish(f"serving_{self.name}")
