"""Request queue + continuous batcher.

Reference seat: the reference serves via ``AnalysisPredictor::Clone`` and
leaves batching to the application; production TPU serving cannot — batch
shape is compile shape.  This scheduler is the Orca-style continuous
batching loop: requests of mixed row counts stream into per-model FIFO
queues, and whenever a worker can take work the scheduler packs the
oldest requests into one batch, padded to a ladder bucket.  While the
workers are busy, arrivals accumulate, so the next batch is bigger —
batch size adapts to load with no per-request recompiles and no fixed
batch-size knob.

Host-side, lock-and-condvar concurrency; nothing here touches the device.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework import flags as _flags
from ..framework.enforce import UnavailableError
from ..profiler import tracing as _tracing
from ..profiler.metrics import default_registry as _registry
from ..utils.monitor import stat_set

# typed serving histograms (docs/METRICS.md inventory): where a request
# waits, how full the batches run, how much of each bucket is padding
_QUEUE_WAIT = _registry().histogram(
    "serving_queue_wait_seconds",
    "Time a request spends in the RequestQueue between submit() and the "
    "continuous batcher packing it (per request).")
_BATCH_ROWS = _registry().histogram(
    "serving_batch_occupancy_rows",
    "Real (un-padded) rows per scheduler-formed batch — how big the "
    "continuous batcher actually runs under load.",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
_PAD_EFFICIENCY = _registry().histogram(
    "serving_padding_efficiency_ratio",
    "rows / bucket per batch: 1.0 = the padded bucket was full, low "
    "values = the ladder is paying for zeros.",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))

# token-level slot accounting for the iteration-level decode loop
# (serving/slots.py, FLAGS_decode_slots): batch-level queue depth says
# nothing about how full the step executable runs — these do.  Published
# through Server.signals() into the PR-16 ClusterSignals snapshot.
SLOT_OCCUPANCY = _registry().gauge(
    "decode_slot_occupancy_ratio",
    "Generating rows / total slots at the latest decode step of the "
    "slot loop — the token-level utilisation of the single-step decode "
    "executable (1.0 = every slot is emitting).",
    labels=("model",))
SLOTS_JOINED = _registry().counter(
    "decode_slots_joined_total",
    "Requests admitted into a decode slot at a token boundary (a join "
    "is a validity-window restart: no recompile, no cache copy).",
    labels=("model",))
SLOTS_RETIRED = _registry().counter(
    "decode_slots_retired_total",
    "Rows retired from the slot loop (eos or per-request token budget) "
    "— retirement frees the slot the same step.",
    labels=("model",))
# per-tenant admission (cluster lifecycle PR): quotas bound how much of
# the shared queue one tenant can hold, so a burst from tenant A fills
# A's allowance and then bounces with a retry_after hint instead of
# growing everyone's p99
TENANT_REJECTS = _registry().counter(
    "serving_tenant_rejections_total",
    "Requests rejected because the tenant was at its pending-quota "
    "(UnavailableError with a retry_after hint; the global queue still "
    "had room for other tenants).",
    labels=("tenant",))
TENANT_PENDING = _registry().gauge(
    "serving_tenant_pending",
    "Requests currently queued per tenant — the quantity the per-tenant "
    "quota caps.",
    labels=("tenant",))
SLOT_TTFT = _registry().histogram(
    "decode_slot_ttft_seconds",
    "Time from slot-loop submit to the request's first emitted token — "
    "the metric chunked prefill exists to keep flat under long-prompt "
    "head-of-line pressure.",
    labels=("model",),
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
             2.5, 5.0))


@dataclass
class Request:
    """One client request: ``rows`` examples for one model."""

    model: str
    inputs: Tuple[np.ndarray, ...]
    rows: int
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=time.perf_counter)
    # request-scoped tracing: the root span opened by Server.submit (None
    # when FLAGS_trace is off / the request was not sampled) plus the
    # monotonic enqueue stamp the queue-wait span/histogram is cut from
    trace: Optional[object] = None
    t_enqueue_mono: float = field(default_factory=time.monotonic)
    # admission class: which tenant's quota this request consumes, and
    # its priority (higher packs first; None = the tenant policy's
    # priority, default 1).  Resolved at put() time.
    tenant: str = "default"
    priority: Optional[int] = None


@dataclass
class Batch:
    """A scheduler-formed batch: FIFO requests totalling ``rows`` rows,
    to be padded up to ``bucket`` rows at execution."""

    model: str
    requests: List[Request]
    rows: int
    bucket: int


def pack_fifo(pending, max_rows: int) -> Tuple[List[Request], int]:
    """Pop requests FIFO while they fit in ``max_rows`` total rows.
    Always takes at least the head request (callers pre-validate that a
    single request fits the ladder).  Pure queue surgery — unit-testable
    without threads."""
    taken: List[Request] = []
    rows = 0
    while pending and (not taken or rows + pending[0].rows <= max_rows):
        r = pending.popleft()
        taken.append(r)
        rows += r.rows
    return taken, rows


class RequestQueue:
    """Bounded multi-model FIFO with condition-variable handoff.

    ``put`` applies backpressure (blocks up to its timeout, then raises
    UnavailableError); ``next_batch`` blocks until work exists, holds the
    batch open up to ``batch_timeout_s`` for more arrivals, then packs
    FIFO up to the model's bucket ceiling.

    Admission is per-tenant aware: ``set_tenant_policy`` caps how many
    pending requests one tenant may hold (default from
    ``FLAGS_serving_tenant_quota``; 0 = unlimited) and assigns a
    priority class — higher priority inserts ahead of lower within a
    model's queue (FIFO within a class), so a quota'd burst from one
    tenant bounces with a retry_after hint while everyone else's wait
    stays flat.
    """

    def __init__(self, capacity: int):
        self._capacity = int(capacity)
        self._cond = threading.Condition()
        self._pending: "OrderedDict[str, deque]" = OrderedDict()  # guarded-by: _cond
        self._depth = 0                                           # guarded-by: _cond
        self._closed = False                                      # guarded-by: _cond
        # drain-rate EWMA (requests/s popped by the batcher): the basis
        # of the machine-readable retry-after hint a backpressure
        # rejection carries — "one slot frees in about 1/rate seconds"
        self._drain_ewma = 0.0                                    # guarded-by: _cond
        self._last_pop_mono: Optional[float] = None
        # staleness epoch for the hint decay: the last instant the queue
        # made progress while work was pending (a pop, or the put that
        # took it from empty).  None until work first arrives.
        self._last_progress_mono: Optional[float] = None
        # per-tenant admission state
        self._tenant_pending: Dict[str, int] = {}                 # guarded-by: _cond
        self._tenant_policy: Dict[str, dict] = {}                 # guarded-by: _cond

    def set_tenant_policy(self, tenant: str,
                          max_pending: Optional[int] = None,
                          priority: Optional[int] = None) -> None:
        """Set a tenant's admission class: ``max_pending`` caps its queued
        requests (None = fall back to ``FLAGS_serving_tenant_quota``),
        ``priority`` orders its requests against other classes (higher
        packs first; default 1)."""
        with self._cond:
            pol = self._tenant_policy.setdefault(tenant, {})
            if max_pending is not None:
                pol["max_pending"] = int(max_pending)
            if priority is not None:
                pol["priority"] = int(priority)
            self._cond.notify_all()

    def _quota_of(self, tenant: str) -> Optional[int]:
        pol = self._tenant_policy.get(tenant)
        if pol and pol.get("max_pending") is not None:
            return pol["max_pending"]
        q = int(_flags.flag("serving_tenant_quota"))
        return q if q > 0 else None

    def _hint_locked(self) -> float:
        """The retry-after estimate (lock held).  Base: 1/drain-rate
        clamped to [10 ms, 5 s], 100 ms before any batch has drained.
        Decay: when work is pending but nothing has drained within
        ``FLAGS_router_stale_after_s``, the hint ramps linearly toward
        the 5 s clamp ceiling over one further stale window — a
        drain-hung replica stops advertising the optimistic cold-start
        default and the router backs off hard instead of hammering it."""
        rate = self._drain_ewma
        hint = 0.1 if rate <= 0 else min(5.0, max(0.01, 1.0 / rate))
        if self._depth > 0 and self._last_progress_mono is not None:
            stale = float(_flags.flag("router_stale_after_s"))
            elapsed = time.monotonic() - self._last_progress_mono
            if stale > 0 and elapsed > stale:
                frac = min(1.0, (elapsed - stale) / stale)
                hint = hint + frac * (5.0 - hint)
        return hint

    def suggest_retry_after(self) -> float:
        """Estimated seconds until a queue slot frees, from the observed
        drain rate (clamped to [10 ms, 5 s]; 100 ms before any batch has
        drained, decaying toward the ceiling once the queue is stuck —
        see ``_hint_locked``).  Callers attach this to UnavailableError
        rejections so a router backs off THIS replica instead of
        evicting it."""
        with self._cond:
            return self._hint_locked()

    # -- producer ------------------------------------------------------------
    def put(self, req: Request, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.perf_counter() + timeout
        tenant = req.tenant or "default"
        with self._cond:
            quota = self._quota_of(tenant)
            while not self._closed and (
                    self._depth >= self._capacity
                    or (quota is not None
                        and self._tenant_pending.get(tenant, 0) >= quota)):
                remaining = None if deadline is None \
                    else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    hint = self._hint_locked()
                    over_quota = quota is not None \
                        and self._tenant_pending.get(tenant, 0) >= quota \
                        and self._depth < self._capacity
                    if over_quota:
                        TENANT_REJECTS.labels(tenant).inc()
                        raise UnavailableError(
                            f"tenant {tenant!r} at pending-quota "
                            f"({quota}); backpressure timeout expired "
                            f"(retry after ~{hint:.3f}s)",
                            retry_after_s=hint)
                    raise UnavailableError(
                        f"serving queue full ({self._capacity} pending); "
                        "backpressure timeout expired "
                        f"(retry after ~{hint:.3f}s)",
                        retry_after_s=hint)
                self._cond.wait(remaining)
                quota = self._quota_of(tenant)
            if self._closed:
                # no hint: a closed queue is not coming back — callers
                # should fail over, not retry here
                raise UnavailableError("serving queue is closed")
            if req.priority is None:
                pol = self._tenant_policy.get(tenant)
                req.priority = int(pol.get("priority", 1)) if pol else 1
            if self._depth == 0:
                # fresh epoch: idle time before this arrival is not
                # drain staleness
                self._last_progress_mono = time.monotonic()
            dq = self._pending.setdefault(req.model, deque())
            if dq and req.priority > (dq[-1].priority or 1):
                # priority insert: ahead of the first strictly-lower
                # class, FIFO within its own (deques stay sorted by
                # priority descending, so one scan suffices)
                idx = len(dq)
                for i, r in enumerate(dq):
                    if (r.priority or 1) < req.priority:
                        idx = i
                        break
                dq.insert(idx, req)
            else:
                dq.append(req)
            self._depth += 1
            self._tenant_pending[tenant] = \
                self._tenant_pending.get(tenant, 0) + 1
            TENANT_PENDING.labels(tenant).set(
                self._tenant_pending[tenant])
            stat_set("serving_queue_depth", self._depth)
            self._cond.notify_all()

    # -- consumer (scheduler thread) -----------------------------------------
    def _oldest_model(self) -> Optional[str]:
        best, best_t = None, None
        for name, dq in self._pending.items():
            if dq and (best_t is None or dq[0].t_enqueue < best_t):
                best, best_t = name, dq[0].t_enqueue
        return best

    def next_batch(self, max_rows_of, bucket_of,
                   batch_timeout_s: float) -> Optional[Batch]:
        """Form the next batch, or None once closed and drained.

        ``max_rows_of(model)`` bounds the pack; ``bucket_of(model, rows)``
        maps packed rows to the ladder bucket.
        """
        with self._cond:
            while True:
                model = self._oldest_model()
                if model is not None:
                    break
                if self._closed:
                    return None
                self._cond.wait(0.1)
            # hold the batch open for stragglers: more arrivals within the
            # window ride this batch instead of paying their own dispatch
            dq = self._pending[model]
            limit = max_rows_of(model)
            if batch_timeout_s > 0:
                deadline = dq[0].t_enqueue + batch_timeout_s
                while (sum(r.rows for r in dq) < limit
                       and not self._closed):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                dq = self._pending[model]
            t_pack0 = time.monotonic()
            taken, rows = pack_fifo(dq, limit)
            self._depth -= len(taken)
            if taken and self._last_pop_mono is not None:
                inst = len(taken) / max(1e-6,
                                        t_pack0 - self._last_pop_mono)
                self._drain_ewma = inst if self._drain_ewma <= 0 \
                    else 0.8 * self._drain_ewma + 0.2 * inst
            if taken:
                self._last_pop_mono = t_pack0
                self._last_progress_mono = t_pack0
            for r in taken:
                t = r.tenant or "default"
                left = self._tenant_pending.get(t, 0) - 1
                if left > 0:
                    self._tenant_pending[t] = left
                else:
                    self._tenant_pending.pop(t, None)
                TENANT_PENDING.labels(t).set(max(0, left))
            stat_set("serving_queue_depth", self._depth)
            self._cond.notify_all()
        bucket = bucket_of(model, rows)
        t_pack1 = time.monotonic()
        _BATCH_ROWS.observe(rows)
        _PAD_EFFICIENCY.observe(rows / bucket if bucket else 0.0)
        for r in taken:
            _QUEUE_WAIT.observe(t_pack0 - r.t_enqueue_mono)
            if r.trace is not None:
                _tracing.child(r.trace, "queue_wait",
                               r.t_enqueue_mono, t_pack0)
                _tracing.child(r.trace, "pack", t_pack0, t_pack1,
                               bucket=bucket, batch_rows=rows,
                               padding_rows=bucket - rows)
        return Batch(model=model, requests=taken, rows=rows, bucket=bucket)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return self._depth

    def signals(self) -> dict:
        """The queue's autoscaling inputs in one locked read: current
        depth, the drain-rate EWMA (requests/s the batcher is actually
        popping), and the same retry-after estimate backpressure
        rejections carry — what cluster/obs.ClusterSignals publishes
        per replica."""
        with self._cond:
            depth, rate = self._depth, self._drain_ewma
            retry = self._hint_locked()
            tenants = {t: n for t, n in self._tenant_pending.items() if n}
        return {"queue_depth": depth,
                "drain_rate_rps": round(rate, 3),
                "retry_after_s": round(retry, 4),
                "tenant_pending": tenants}

    def drain(self) -> List[Request]:
        """Pop everything still pending (stop without serving them)."""
        with self._cond:
            out: List[Request] = []
            for dq in self._pending.values():
                out.extend(dq)
                dq.clear()
            self._depth = 0
            for t in list(self._tenant_pending):
                TENANT_PENDING.labels(t).set(0)
            self._tenant_pending.clear()
            stat_set("serving_queue_depth", 0)
            self._cond.notify_all()
            return out
