"""paddle.text.datasets parity: the reference's 7 text dataset loaders.

Reference: python/paddle/text/datasets/{conll05,imdb,imikolov,movielens,
uci_housing,wmt14,wmt16}.py.  Zero-egress container policy (same as
vision/datasets): each loader parses the REFERENCE'S record format when a
local ``data_file`` is supplied (the formats the reference downloads —
tarballs of tokenized text, ``::``-separated .dat files, space-separated
housing rows), and otherwise generates deterministic synthetic records with
the right structure so pipelines and tests run without network.
"""
from __future__ import annotations

import collections
import gzip
import os
import re
import string
import tarfile
import zipfile

import numpy as np

from ...io.dataset import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "MovieInfo", "UserInfo"]


def _to_text(b):
    return b.decode("utf-8", "ignore") if isinstance(b, bytes) else b


# ---------------------------------------------------------------------------
# UCIHousing
# ---------------------------------------------------------------------------

class UCIHousing(Dataset):
    """uci_housing.py: 13 normalized features + 1 target per row, 80/20
    train/test split.  ``data_file`` is the space-separated housing.data
    format; synthetic fallback keeps the 14-column contract."""

    FEATURE_NUM = 14

    def __init__(self, data_file=None, mode="train", download=True,
                 synthetic_size=120):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_file is not None and os.path.exists(data_file):
            data = np.fromfile(data_file, sep=" ")
            data = data.reshape(len(data) // self.FEATURE_NUM,
                                self.FEATURE_NUM)
        else:
            rng = np.random.RandomState(42)
            data = rng.rand(synthetic_size, self.FEATURE_NUM) * 10
        maxs, mins = data.max(axis=0), data.min(axis=0)
        avgs = data.mean(axis=0)
        for i in range(self.FEATURE_NUM - 1):
            data[:, i] = (data[:, i] - avgs[i]) / \
                max(maxs[i] - mins[i], 1e-6)
        offset = int(len(data) * 0.8)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype("float32"), row[-1:].astype("float32"))

    def __len__(self):
        return len(self.data)


# ---------------------------------------------------------------------------
# Imdb
# ---------------------------------------------------------------------------

class Imdb(Dataset):
    """imdb.py: aclImdb tarball of train/test pos/neg docs; word dict built
    from corpus frequency (> cutoff), docs mapped to ids, label 0 = pos,
    1 = neg (the reference's ordering)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True, synthetic_size=64):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_file is not None and os.path.exists(data_file):
            docs_by_split = self._tokenize_tar(data_file)
            self.word_idx = self._build_dict(
                [d for split in docs_by_split.values()
                 for lab in split.values() for d in lab], cutoff)
            unk = self.word_idx["<unk>"]
            self.docs, self.labels = [], []
            for label_name, label in (("pos", 0), ("neg", 1)):
                for doc in docs_by_split[self.mode][label_name]:
                    self.docs.append([self.word_idx.get(w, unk)
                                      for w in doc])
                    self.labels.append(label)
        else:
            rng = np.random.RandomState(0 if self.mode == "train" else 1)
            vocab = 512
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            self.word_idx["<unk>"] = vocab
            self.docs = [list(rng.randint(0, vocab,
                                          rng.randint(5, 40)))
                         for _ in range(synthetic_size)]
            self.labels = list(rng.randint(0, 2, synthetic_size))

    @staticmethod
    def _tokenize_tar(path):
        pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        out = {"train": {"pos": [], "neg": []},
               "test": {"pos": [], "neg": []}}
        trans = str.maketrans("", "", string.punctuation)
        with tarfile.open(path) as tf:
            for m in tf:
                g = pat.match(m.name)
                if not g:
                    continue
                text = _to_text(tf.extractfile(m).read()).rstrip("\n\r")
                out[g.group(1)][g.group(2)].append(
                    text.translate(trans).lower().split())
        return out

    @staticmethod
    def _build_dict(docs, cutoff):
        freq = collections.defaultdict(int)
        for doc in docs:
            for w in doc:
                freq[w] += 1
        kept = sorted([kv for kv in freq.items() if kv[1] > cutoff],
                      key=lambda kv: (-kv[1], kv[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def __getitem__(self, idx):
        return (np.array(self.docs[idx]), np.array([self.labels[idx]]))

    def __len__(self):
        return len(self.docs)


# ---------------------------------------------------------------------------
# Imikolov (PTB)
# ---------------------------------------------------------------------------

class Imikolov(Dataset):
    """imikolov.py: PTB language-model corpus; 'NGRAM' mode yields
    window_size-grams, 'SEQ' mode yields (<s>+sent, sent+<e>) pairs."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True,
                 synthetic_size=128):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        assert mode.lower() in ("train", "test"), mode
        self.data_type = data_type.upper()
        self.mode = mode.lower()
        self.window_size = window_size

        if data_file is not None and os.path.exists(data_file):
            train_lines, test_lines = self._read_tar(data_file)
            self.word_idx = self._build_dict(train_lines + test_lines,
                                             min_word_freq)
            lines = train_lines if self.mode == "train" else test_lines
        else:
            rng = np.random.RandomState(0 if self.mode == "train" else 1)
            # vocab/line counts sized so typical words clear the DEFAULT
            # min_word_freq=50 (128 lines x ~8 words / 16 vocab ≈ 64
            # appearances) — the filtered dict stays usable out of the box
            vocab = [f"w{i}" for i in range(16)]
            lines = [" ".join(rng.choice(vocab, rng.randint(4, 12)))
                     for _ in range(synthetic_size)]
            # same frequency-filtered dict build as the real-file path, so
            # min_word_freq is honored either way
            self.word_idx = self._build_dict(lines, min_word_freq)
        self._load(lines)

    @staticmethod
    def _read_tar(path):
        with tarfile.open(path) as tf:
            tr = tf.extractfile("./simple-examples/data/ptb.train.txt")
            va = tf.extractfile("./simple-examples/data/ptb.valid.txt")
            return ([_to_text(l) for l in tr.readlines()],
                    [_to_text(l) for l in va.readlines()])

    @staticmethod
    def _build_dict(lines, min_word_freq):
        freq = collections.defaultdict(int)
        for l in lines:
            for w in l.strip().split():
                freq[w] += 1
            freq["<s>"] += 1
            freq["<e>"] += 1
        freq.pop("<unk>", None)
        kept = sorted([kv for kv in freq.items() if kv[1] > min_word_freq],
                      key=lambda kv: (-kv[1], kv[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self, lines):
        unk = self.word_idx["<unk>"]
        self.data = []
        for l in lines:
            if self.data_type == "NGRAM":
                assert self.window_size > -1, "Invalid gram length"
                toks = ["<s>"] + l.strip().split() + ["<e>"]
                if len(toks) >= self.window_size:
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    for i in range(self.window_size, len(ids) + 1):
                        self.data.append(tuple(ids[i - self.window_size:i]))
            else:
                toks = l.strip().split()
                ids = [self.word_idx.get(w, unk) for w in toks]
                unk2 = self.word_idx["<unk>"]
                src = [self.word_idx.get("<s>", unk2)] + ids
                trg = ids + [self.word_idx.get("<e>", unk2)]
                if self.window_size > 0 and len(src) > self.window_size:
                    continue
                self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


# ---------------------------------------------------------------------------
# Movielens
# ---------------------------------------------------------------------------

class MovieInfo:
    """movielens.py:37 — id, categories, title of a movie."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    """movielens.py:62 — id, gender (M=0), bucketed age, job."""

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = self.AGES.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), gender({'M' if self.is_male else 'F'}), "
                f"age({self.AGES[self.age]}), job({self.job_id})>")


class Movielens(Dataset):
    """movielens.py: ml-1m zip (movies.dat/users.dat/ratings.dat,
    ``::``-separated); each record = user value + movie value + [[rating]],
    rating rescaled to r*2-5."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True, synthetic_size=64):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.test_ratio = test_ratio
        # private RandomState: the reference reseeds global np.random
        # (movielens.py), which would silently correlate every other
        # consumer of global numpy randomness in the process
        self._rng = np.random.RandomState(rand_seed)
        if data_file is not None and os.path.exists(data_file):
            self._load_zip(data_file)
        else:
            # mode-distinct seed so the synthetic 'test' split is not the
            # training set
            self._synthesize(synthetic_size,
                             rand_seed + (0 if self.mode == "train" else 1))

    def _load_zip(self, path):
        pat = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(path) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = _to_text(line).strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    m = pat.match(title)
                    title = m.group(1) if m else title
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
                    title_words.update(w.lower() for w in title.split())
            self.movie_title_dict = {w: i for i, w in
                                     enumerate(sorted(title_words))}
            self.categories_dict = {c: i for i, c in
                                    enumerate(sorted(categories))}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job = \
                        _to_text(line).strip().split("::")[:4]
                    self.user_info[int(uid)] = UserInfo(uid, gender, age,
                                                        job)
            self.data = []
            is_test = self.mode == "test"
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (self._rng.random_sample() < self.test_ratio) != is_test:
                        continue
                    uid, mid, rating = \
                        _to_text(line).strip().split("::")[:3]
                    mov = self.movie_info[int(mid)]
                    usr = self.user_info[int(uid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[float(rating) * 2 - 5.0]])

    def _synthesize(self, n, seed):
        rng = np.random.RandomState(seed)
        cats = ["Action", "Comedy", "Drama"]
        self.categories_dict = {c: i for i, c in enumerate(cats)}
        self.movie_title_dict = {f"t{i}": i for i in range(32)}
        self.movie_info = {
            i: MovieInfo(i, [cats[i % 3]], f"t{i % 32}")
            for i in range(1, 20)}
        self.user_info = {
            i: UserInfo(i, "M" if i % 2 else "F",
                        UserInfo.AGES[i % 7], i % 10)
            for i in range(1, 10)}
        self.data = []
        for _ in range(n):
            usr = self.user_info[int(rng.randint(1, 10))]
            mov = self.movie_info[int(rng.randint(1, 20))]
            rating = float(rng.randint(1, 6)) * 2 - 5.0
            self.data.append(usr.value()
                             + mov.value(self.categories_dict,
                                         self.movie_title_dict)
                             + [[rating]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


# ---------------------------------------------------------------------------
# Conll05st (SRL)
# ---------------------------------------------------------------------------

class Conll05st(Dataset):
    """conll05.py: WSJ test split of CoNLL-2005 SRL.  Parses the
    words/props column format (one token per line, blank line ends a
    sentence; props column 0 = verbs, later columns = per-predicate
    bracketed role spans) into (sentence, predicate, BIO labels) triples;
    __getitem__ emits the 9-feature SRL record (words, 5 context windows,
    predicate, mark, labels) exactly as the reference."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True, synthetic_size=32):
        del mode  # reference serves the same WSJ test split for both
        if data_file is not None and os.path.exists(data_file):
            words_lines, props_lines = self._read_tar(data_file)
            self._parse(words_lines, props_lines)
        else:
            self._synthesize(synthetic_size)
        self.word_dict = self._dict_or_build(word_dict_file,
                                             self._corpus_words())
        self.predicate_dict = self._dict_or_build(
            verb_dict_file, sorted(set(self.predicates)))
        self.label_dict = self._dict_or_build(
            target_dict_file, self._label_names())

    @staticmethod
    def _read_tar(path):
        with tarfile.open(path) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as w, \
                    gzip.GzipFile(fileobj=pf) as p:
                return ([_to_text(l) for l in w.readlines()],
                        [_to_text(l) for l in p.readlines()])

    def _parse(self, words_lines, props_lines):
        self.sentences, self.predicates, self.labels = [], [], []
        sentence, one_seg = [], []
        for word, label in zip(words_lines, props_lines):
            word = word.strip()
            cols = label.strip().split()
            if not cols:                      # sentence boundary
                self._emit(sentence, one_seg)
                sentence, one_seg = [], []
                continue
            sentence.append(word)
            one_seg.append(cols)
        self._emit(sentence, one_seg)

    def _emit(self, sentence, one_seg):
        if not one_seg:
            return
        ncols = len(one_seg[0])
        columns = [[row[i] for row in one_seg] for i in range(ncols)]
        verbs = [v for v in columns[0] if v != "-"]
        for i, col in enumerate(columns[1:]):
            lbl_seq = []
            cur_tag, in_br = "O", False
            for tok in col:
                if tok == "*" and not in_br:
                    lbl_seq.append("O")
                elif tok == "*" and in_br:
                    lbl_seq.append("I-" + cur_tag)
                elif tok == "*)":
                    lbl_seq.append("I-" + cur_tag)
                    in_br = False
                elif "(" in tok and ")" in tok:
                    cur_tag = tok[1:tok.find("*")]
                    lbl_seq.append("B-" + cur_tag)
                    in_br = False
                elif "(" in tok:
                    cur_tag = tok[1:tok.find("*")]
                    lbl_seq.append("B-" + cur_tag)
                    in_br = True
                else:
                    raise ValueError(f"unexpected props token {tok!r}")
            if i >= len(verbs) or "B-V" not in lbl_seq:
                continue
            self.sentences.append(list(sentence))
            self.predicates.append(verbs[i])
            self.labels.append(lbl_seq)

    def _synthesize(self, n):
        rng = np.random.RandomState(0)
        vocab = [f"w{i}" for i in range(40)]
        verbs = ["run", "eat", "see"]
        self.sentences, self.predicates, self.labels = [], [], []
        for _ in range(n):
            ln = int(rng.randint(4, 9))
            sent = list(rng.choice(vocab, ln))
            vi = int(rng.randint(0, ln))
            verb = verbs[int(rng.randint(0, 3))]
            sent[vi] = verb
            lbl = ["O"] * ln
            lbl[vi] = "B-V"
            if vi + 1 < ln:
                lbl[vi + 1] = "B-A1"
            self.sentences.append(sent)
            self.predicates.append(verb)
            self.labels.append(lbl)

    def _corpus_words(self):
        seen = []
        for s in self.sentences:
            seen.extend(w.lower() for w in s)
        seen.extend(["bos", "eos"])
        return sorted(set(seen))

    def _label_names(self):
        names = set()
        for lbl in self.labels:
            names.update(lbl)
        return sorted(names)

    @staticmethod
    def _dict_or_build(path, fallback_items):
        if path is not None and os.path.exists(path):
            with open(path) as f:
                return {l.strip(): i for i, l in enumerate(f)
                        if l.strip()}
        return {w: i for i, w in enumerate(fallback_items)}

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        sentence = [w.lower() for w in self.sentences[idx]]
        predicate = self.predicates[idx]
        labels = self.labels[idx]
        n = len(sentence)
        unk = self.word_dict.get("<unk>", 0)
        vi = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, name, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                               (0, "0", None), (1, "p1", "eos"),
                               (2, "p2", "eos")):
            j = vi + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = pad
        word_idx = [self.word_dict.get(w, unk) for w in sentence]
        mk = lambda w: [self.word_dict.get(w, unk)] * n  # noqa: E731
        pred_idx = [self.predicate_dict.get(predicate, 0)] * n
        label_idx = [self.label_dict.get(l, 0) for l in labels]
        return (np.array(word_idx), np.array(mk(ctx["n2"])),
                np.array(mk(ctx["n1"])), np.array(mk(ctx["0"])),
                np.array(mk(ctx["p1"])), np.array(mk(ctx["p2"])),
                np.array(pred_idx), np.array(mark), np.array(label_idx))

    def __len__(self):
        return len(self.sentences)


# ---------------------------------------------------------------------------
# WMT14 / WMT16
# ---------------------------------------------------------------------------

class _WMTBase(Dataset):
    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    @staticmethod
    def _synth_pairs(n, seed):
        rng = np.random.RandomState(seed)
        return [(" ".join(f"s{j}" for j in
                          rng.randint(0, 30, rng.randint(3, 9))),
                 " ".join(f"t{j}" for j in
                          rng.randint(0, 30, rng.randint(3, 9))))
                for _ in range(n)]


class WMT14(_WMTBase):
    """wmt14.py: tarball with {src,trg}.dict (one word per line, rank =
    id; rows 0-2 are <s>, <e>, <unk>) and train/test files of
    tab-separated sentence pairs.  Records: (<s>+src+<e> ids, <s>+trg
    ids, trg+<e> ids)."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True, synthetic_size=48):
        assert mode.lower() in ("train", "test", "gen"), mode
        self.mode = mode.lower()
        if data_file is not None and os.path.exists(data_file):
            self._load_tar(data_file, dict_size)
        else:
            self._load_synth(synthetic_size)

    def _load_tar(self, path, dict_size):
        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if size >= 0 and i >= size:
                    break
                out[_to_text(line).strip()] = i
            return out

        with tarfile.open(path) as tf:
            names = [m.name for m in tf if m.name.endswith("src.dict")]
            self.src_dict = to_dict(tf.extractfile(names[0]), dict_size)
            names = [m.name for m in tf if m.name.endswith("trg.dict")]
            self.trg_dict = to_dict(tf.extractfile(names[0]), dict_size)
            suffix = f"{self.mode}/{self.mode}"
            names = [m.name for m in tf if m.name.endswith(suffix)]
            pairs = []
            for name in names:
                for line in tf.extractfile(name):
                    parts = _to_text(line).strip().split("\t")
                    if len(parts) == 2:
                        pairs.append((parts[0], parts[1]))
        self._encode(pairs)

    def _load_synth(self, n):
        words = [f"s{i}" for i in range(30)] + [f"t{i}" for i in range(30)]
        base = {"<s>": 0, "<e>": 1, "<unk>": 2}
        self.src_dict = dict(base, **{w: i + 3 for i, w in
                                      enumerate(words[:30])})
        self.trg_dict = dict(base, **{w: i + 3 for i, w in
                                      enumerate(words[30:])})
        self._encode(self._synth_pairs(n, 0 if self.mode == "train" else 1))

    def _encode(self, pairs):
        s_unk = self.src_dict.get("<unk>", 2)
        t_unk = self.trg_dict.get("<unk>", 2)
        start, end = 0, 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for src, trg in pairs:
            si = [start] + [self.src_dict.get(w, s_unk)
                            for w in src.split()] + [end]
            ti = [self.trg_dict.get(w, t_unk) for w in trg.split()]
            self.src_ids.append(si)
            self.trg_ids.append([start] + ti)
            self.trg_ids_next.append(ti + [end])


class WMT16(_WMTBase):
    """wmt16.py: tarball with wmt16/{train,test,val} files of
    tab-separated en/de pairs; dictionaries built from corpus frequency
    to {src,trg}_dict_size with <s>/<e>/<unk> reserved.  ``lang`` picks
    the source column."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True,
                 synthetic_size=48):
        assert mode.lower() in ("train", "test", "val"), mode
        assert src_dict_size > 0 and trg_dict_size > 0, \
            "dict_size should be set as positive number"
        self.mode = mode.lower()
        self.lang = lang
        if data_file is not None and os.path.exists(data_file):
            self._load_tar(data_file, src_dict_size, trg_dict_size)
        else:
            self._load_synth(synthetic_size, src_dict_size, trg_dict_size)

    def _build_dict(self, lines, col, size):
        freq = collections.defaultdict(int)
        for l in lines:
            parts = l.strip().split("\t")
            if len(parts) == 2:
                for w in parts[col].split():
                    freq[w] += 1
        kept = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        d = {"<s>": 0, "<e>": 1, "<unk>": 2}
        for w, _ in kept[:max(size - 3, 0)]:
            d[w] = len(d)
        return d

    def _load_tar(self, path, src_size, trg_size):
        with tarfile.open(path) as tf:
            lines = [_to_text(l) for l in
                     tf.extractfile(f"wmt16/{self.mode}").readlines()]
            train_lines = [_to_text(l) for l in
                           tf.extractfile("wmt16/train").readlines()] \
                if self.mode != "train" else lines
        src_col = 0 if self.lang == "en" else 1
        self.src_dict = self._build_dict(train_lines, src_col, src_size)
        self.trg_dict = self._build_dict(train_lines, 1 - src_col,
                                         trg_size)
        self._encode(lines, src_col)

    def _load_synth(self, n, src_size, trg_size):
        pairs = self._synth_pairs(n, 0 if self.mode == "train" else 1)
        lines = [f"{s}\t{t}" for s, t in pairs]
        src_col = 0 if self.lang == "en" else 1
        self.src_dict = self._build_dict(lines, src_col, src_size)
        self.trg_dict = self._build_dict(lines, 1 - src_col, trg_size)
        self._encode(lines, src_col)

    def _encode(self, lines, src_col):
        start, end = self.src_dict["<s>"], self.src_dict["<e>"]
        unk = self.src_dict["<unk>"]
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for l in lines:
            parts = l.strip().split("\t")
            if len(parts) != 2:
                continue
            si = [start] + [self.src_dict.get(w, unk)
                            for w in parts[src_col].split()] + [end]
            ti = [self.trg_dict.get(w, unk)
                  for w in parts[1 - src_col].split()]
            self.src_ids.append(si)
            self.trg_ids.append([start] + ti)
            self.trg_ids_next.append(ti + [end])

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d
