"""paddle.text parity package: text models + datasets.

Reference parity: python/paddle/text/ (RNN-era model zoo + datasets). The TPU
build additionally ships the transformer-LM family (bert.py) because BERT-base
pretraining is a headline benchmark workload (BASELINE.json config 3).
"""
from . import models, datasets, generation, speculative  # noqa: F401
from .models import (  # noqa: F401
    BertModel, BertConfig, BertForPretraining, GPTModel, GPTConfig,
)
from .generation import Generator, generate  # noqa: F401
from .speculative import SpeculativeGenerator  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
from ..ops.decode import viterbi_decode  # noqa: F401


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder over the viterbi_decode op
    (ops/decode.py; reference 2.x paddle.text.viterbi_decode)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
