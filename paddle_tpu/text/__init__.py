"""paddle.text parity package: text models + datasets.

Reference parity: python/paddle/text/ (RNN-era model zoo + datasets). The TPU
build additionally ships the transformer-LM family (bert.py) because BERT-base
pretraining is a headline benchmark workload (BASELINE.json config 3).
"""
from . import models  # noqa: F401
from .models import (  # noqa: F401
    BertModel, BertConfig, BertForPretraining, GPTModel, GPTConfig,
)
