"""Draft/target speculative decoding compiled as ONE jitted scan.

Plain ``generate()`` (text/generation.py) pays one full target-model
forward per generated token — the dominant cost of autoregressive
serving.  Speculative decoding multiplies tokens per target pass without
changing the output:

  * a small **draft** model proposes ``gamma`` tokens autoregressively
    from its OWN ring cache (``gamma + 1`` cheap single-token forwards —
    the extra one back-fills the last proposal's K/V so the draft cache
    stays committed-prefix-consistent at every acceptance count);
  * the **target** scores all ``gamma + 1`` positions in a SINGLE
    batched verify forward — ``forward_cached`` with a ``gamma + 1``-wide
    ``cache_position`` block write (ring_block_write splits the write at
    the ring boundary);
  * **greedy acceptance** walks the longest prefix where the draft's
    proposal equals the target's own argmax; everything after the first
    disagreement is discarded and the target's token at the disagreement
    point is committed instead — so every emitted token is the target's
    greedy choice over the exact committed prefix and the output is
    bit-identical to plain greedy decode of the target, whatever the
    draft proposes (a random draft only costs speed, never correctness);
  * **rejection rolls both caches back by rewinding cache_position** —
    the ring caches take traced positions, so rollback is a counter
    move, not a copy: stale K/V rows beyond the committed length fall
    outside the validity mask and are overwritten by the next block;
  * batched rows advance in LOCKSTEP (the per-step acceptance is the
    minimum over rows): cache positions stay scalar, so the whole
    propose -> verify -> accept -> rewind loop is one
    ``lax.while_loop`` body inside one jitted program.  At batch 1 this
    is exact speculative decoding; at larger batches the slowest row
    paces the batch (the acceptance-rate histogram shows what that
    costs).

Exactly TWO executables run per ``generate()`` — the joint prefill
(target + draft caches filled in one program) and the scanned
speculative step — ledgered at the Generator's ``generate:<model>`` site
(kinds ``spec_prefill`` / ``spec_decode``), so the zero-per-token- and
zero-steady-state-compile proofs carry over unchanged to the serving
engine's warm-up grid (serving/decode.py registers a draft/target
``DecodeModelSpec`` pair under ``FLAGS_spec_decode``).

Acceptance telemetry: ``spec_proposed_tokens_total`` /
``spec_accepted_tokens_total`` counters and the ``spec_acceptance_ratio``
histogram in the typed MetricsRegistry; traced requests get ``draft`` /
``verify`` child spans under the decode span (durations estimated by the
models' parameter-count ratio — the scan is one device program, so the
host cannot fence the phases; the spans say so via ``estimated=True``).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import flags as _flags
from ..framework.enforce import InvalidArgumentError as _InvalidArgument
from ..framework.functional import layer_state as _layer_state
from ..profiler import tracing as _tracing
from ..profiler.metrics import default_registry as _registry
from .generation import Generator as _Generator
from .generation import (_apply_layer, _aval, _slice_row, _splice_row)

__all__ = ["SpeculativeGenerator"]
SPEC_PROPOSED = _registry().counter(
    "spec_proposed_tokens_total",
    "Draft tokens proposed to the target verifier by speculative "
    "decoding (gamma per speculative step), per generate site.",
    labels=("model",))
SPEC_ACCEPTED = _registry().counter(
    "spec_accepted_tokens_total",
    "Proposed draft tokens the target verifier accepted (the longest "
    "agreeing prefix, minimum over batch rows), per generate site.",
    labels=("model",))
SPEC_ACCEPT_RATIO = _registry().histogram(
    "spec_acceptance_ratio",
    "Per-generate() draft acceptance rate (accepted / proposed): the "
    "knob that decides whether gamma pays for itself.",
    labels=("model",),
    buckets=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0))


def _param_bytes(params):
    return sum(int(v.size) * int(v.dtype.itemsize)
               for v in jax.tree_util.tree_leaves(params))


class SpeculativeGenerator(_Generator):
    """Compiled draft/target speculative decoding for one model pair.

    The Generator contract is preserved exactly — ``prefill(ids, start,
    C)`` returns ``(caches, next-token logits)`` and ``decode(...)``
    returns generated ids ``[B, steps]`` — so the serving decode runtime
    and the bench harness drive it unchanged; only the cache payload is
    now the (target, draft) pair and the decode program is the
    speculative while-loop.  Greedy only: ``beam_size > 1`` raises
    (beam search re-scores whole beams every step — there is no draft
    shortcut to verify against).
    """

    _PREFILL_KIND = "spec_prefill"
    _DECODE_KIND = "spec_decode"

    def __init__(self, layer, draft, site: Optional[str] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 max_len: Optional[int] = None,
                 gamma: Optional[int] = None):
        if not hasattr(draft, "forward_cached") \
                or not hasattr(draft, "init_cache"):
            raise _InvalidArgument(
                f"draft {type(draft).__name__} does not implement the "
                "incremental-decoding contract (init_cache + "
                "forward_cached) — see text.models.GPTModel")
        tv = getattr(getattr(layer, "config", None), "vocab_size", None)
        dv = getattr(getattr(draft, "config", None), "vocab_size", None)
        if tv is not None and dv is not None and int(tv) != int(dv):
            raise _InvalidArgument(
                f"draft vocab ({dv}) must match the target vocab ({tv}): "
                "acceptance compares token ids")
        draft.eval()
        self._draft = draft
        g = int(gamma if gamma is not None else _flags.flag("spec_gamma"))
        if g < 1:
            raise _InvalidArgument(f"gamma must be >= 1, got {g}")
        self._gamma = g
        self.last_stats = None
        super().__init__(layer, site=site, seq_buckets=seq_buckets,
                         max_len=max_len)
        # host-side draft/verify attribution ratio for traced spans:
        # both models run ~gamma+1 token-forwards per step, so wall time
        # splits roughly by parameter bytes (annotated estimated=True)
        db = _param_bytes(self._d_params)
        tb = _param_bytes(self._params)
        self._draft_fraction = db / max(db + tb, 1)

    @property
    def gamma(self) -> int:
        return self._gamma

    def refresh_state(self):
        super().refresh_state()
        self._d_params, self._d_buffers = _layer_state(self._draft)

    def _state_avals(self):
        return super()._state_avals() + (
            jax.tree_util.tree_map(_aval, self._d_params),
            jax.tree_util.tree_map(_aval, self._d_buffers))

    def _state_args(self):
        return super()._state_args() + (self._d_params, self._d_buffers)

    def cache_bucket(self, prefill: int, steps: int) -> int:
        """The verify block overshoots the requested steps by up to
        gamma tokens (plus the draft back-fill token), so the cache
        bucket must leave that slack — rollback rewinds the counter, but
        the block WRITE must land inside the ring."""
        return super().cache_bucket(prefill, int(steps) + self._gamma + 1)

    # -- the two pure programs ----------------------------------------------
    def _init_draft_cache_raw(self, B, C):
        ring = self._draft.init_cache(B, C)
        from ..framework.tensor import unwrap
        return [tuple(unwrap(p) for p in c) for c in ring]

    def _build_prefill(self, B, P, C):
        def prefill(tp, tb, dp, db, ids, start):
            t_logits, t_cache = _apply_layer(
                self._layer, tp, tb, ids, self._init_cache_raw(B, C),
                jnp.int32(0), start)
            # the draft consumes the same left-padded prompt so both
            # caches share positions — ONE executable fills both
            _, d_cache = _apply_layer(
                self._draft, dp, db, ids, self._init_draft_cache_raw(B, C),
                jnp.int32(0), start)
            return (t_cache, d_cache), \
                t_logits[:, -1, :].astype(jnp.float32)
        return prefill

    def _build_decode(self, B, C, steps, beam, end):
        if beam != 1:
            raise _InvalidArgument(
                "speculative decoding is greedy-only (beam search "
                "re-scores whole beams — use beam_size=1 or drop the "
                "draft model)")
        gamma = self._gamma
        G1 = gamma + 1
        W = steps + G1                     # emit buffer rows (overshoot)
        target, draft = self._layer, self._draft

        def decode(tp, tb, dp, db, caches, logits0, start, pos0):
            t_cache0, d_cache0 = caches
            cur0 = jnp.argmax(logits0, axis=-1).astype(jnp.int32)
            # [W, B] so the traced-position block write lands on the
            # SUBLANE dim with lanes fully spanned (the exempt pattern)
            buf0 = jnp.zeros((W, B), jnp.int32)
            init = (t_cache0, d_cache0, cur0, jnp.asarray(pos0, jnp.int32),
                    jnp.int32(0), jnp.zeros((B,), bool),
                    jnp.int32(0), jnp.int32(0), jnp.int32(0), buf0)

            def cond(carry):
                return carry[4] < steps

            def body(carry):
                (t_cache, d_cache, cur, t_pos, out_pos, finished,
                 accepted, proposed, nsteps, buf) = carry

                # -- propose: gamma+1 draft forwards; iteration i feeds
                # token i of the block and writes its K/V, so the last
                # proposal's row is back-filled and the draft cache is a
                # valid committed prefix at ANY acceptance count
                def dstep(dc, _):
                    cache, tok, p = dc
                    lg, cache = _apply_layer(draft, dp, db, tok[:, None],
                                             cache, p, start)
                    nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
                    return (cache, nxt, p + 1), tok

                (d_cache, _, _), fed = lax.scan(
                    dstep, (d_cache, cur, t_pos), None, length=G1)
                v_in = jnp.transpose(fed)          # [B, G1]: cur, d1..dγ

                # -- verify: ONE gamma+1-wide target forward; the block
                # write lands at t_pos (rollback later = rewind t_pos)
                v_logits, t_cache = _apply_layer(target, tp, tb, v_in,
                                                 t_cache, t_pos, start)
                g = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)

                # -- accept: longest prefix where the draft agreed with
                # the target's own argmax; lockstep = min over rows
                # (finished rows report gamma so they never pace)
                match = (v_in[:, 1:] == g[:, :-1]).astype(jnp.int32)
                n_row = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                n = jnp.min(jnp.where(finished, gamma, n_row)) \
                    .astype(jnp.int32)
                ncommit = n + 1                    # block tokens emitted
                cur_next = jnp.take_along_axis(
                    g, jnp.broadcast_to(n, (B,))[:, None], axis=1)[:, 0]

                # -- emit: the committed block is v_in[:, :ncommit]; eos
                # freezes rows exactly like the greedy scan (every
                # position after an eos — or on an already-finished
                # row — reads eos)
                is_end = (v_in == jnp.int32(end))
                before = (jnp.cumsum(is_end.astype(jnp.int32), axis=1)
                          - is_end.astype(jnp.int32))
                e = jnp.where(finished[:, None] | (before > 0),
                              jnp.int32(end), v_in)
                col = jnp.arange(G1, dtype=jnp.int32)
                finished2 = finished | jnp.any(
                    (e == jnp.int32(end)) & (col[None, :] < ncommit),
                    axis=1)
                cur_next = jnp.where(finished2, jnp.int32(end), cur_next)
                buf = lax.dynamic_update_slice(
                    buf, jnp.transpose(e), (out_pos, jnp.int32(0)))

                # -- rewind: both caches roll back to the committed
                # length by moving the position counter; the rejected
                # rows are dead weight outside the validity window
                return (t_cache, d_cache, cur_next, t_pos + ncommit,
                        out_pos + ncommit, finished2, accepted + n,
                        proposed + jnp.int32(gamma), nsteps + 1, buf)

            out = lax.while_loop(cond, body, init)
            toks = jnp.transpose(out[9])[:, :steps]
            return toks, out[6], out[7], out[8]

        return decode

    # -- slot-loop programs (serving/slots.py) -------------------------------
    def _build_step(self, S, C, end):
        """ONE speculative step over ``S`` slot rows — the while-loop
        body hoisted so the host owns the loop.  Two slot-specific
        inputs: ``active`` keeps empty/mid-prefill rows from pacing the
        lockstep acceptance (they report gamma, like finished rows);
        ``max_commit`` clamps the commit
        count so the variable stride lands EXACTLY on the host's next
        chunk/activation boundary — committing fewer tokens than the
        target accepted is always exact (the next token is the target's
        argmax at the clamped position), it only costs speed."""
        gamma = self._gamma
        G1 = gamma + 1
        target, draft = self._layer, self._draft

        def step(tp, tb, dp, db, caches, cur, start, finished, active,
                 pos, max_commit):
            t_cache, d_cache = caches
            cur_safe = jnp.where(active, cur, jnp.int32(0))

            def dstep(dc, _):
                cache, tok, p = dc
                lg, cache = _apply_layer(draft, dp, db, tok[:, None],
                                         cache, p, start)
                nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
                return (cache, nxt, p + 1), tok

            (d_new, _, _), fed = lax.scan(
                dstep, (d_cache, cur_safe, pos), None, length=G1)
            v_in = jnp.transpose(fed)              # [S, G1]
            v_logits, t_new = _apply_layer(target, tp, tb, v_in, t_cache,
                                           pos, start)
            g = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)
            match = (v_in[:, 1:] == g[:, :-1]).astype(jnp.int32)
            n_row = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            n = jnp.min(jnp.where(finished | ~active, gamma, n_row)) \
                .astype(jnp.int32)
            ncommit = jnp.minimum(n + 1, max_commit)
            cur_next = jnp.take_along_axis(
                g, jnp.broadcast_to(ncommit - 1, (S,))[:, None],
                axis=1)[:, 0]
            is_end = (v_in == jnp.int32(end))
            before = (jnp.cumsum(is_end.astype(jnp.int32), axis=1)
                      - is_end.astype(jnp.int32))
            e = jnp.where(finished[:, None] | (before > 0),
                          jnp.int32(end), v_in)
            col = jnp.arange(G1, dtype=jnp.int32)
            finished2 = finished | jnp.any(
                (e == jnp.int32(end)) & (col[None, :] < ncommit), axis=1)
            cur_next = jnp.where(finished2, jnp.int32(end), cur_next)
            # no per-row cache blend: both caches are donated and a
            # blend would force a full-plane protective copy per step —
            # inactive rows' garbage block [pos, pos+G1) is dead by the
            # host chunk schedule (slots._dispatch_chunks) and by the
            # next active dispatch rewriting [pos', pos'+G1) before any
            # commit exposes it
            return (t_new, d_new), cur_next, finished2, e, ncommit, n

        return step

    def _build_chunk(self, S, T, C):
        """One JOINT prefill chunk: target and draft both consume the
        joining row's ``T`` prompt tokens at the block position, so the
        two caches stay position-aligned exactly like the joint prefill
        executable.  Single-row like the plain chunk — both forwards
        run at batch 1 over the row's sliced planes.  Returns the
        target's last-column logits."""
        target, draft = self._layer, self._draft

        def chunk(tp, tb, dp, db, caches, ids, start, rowidx, pos):
            t_cache, d_cache = caches
            t_sub = _slice_row(t_cache, rowidx)
            d_sub = _slice_row(d_cache, rowidx)
            t_logits, t_new = _apply_layer(target, tp, tb, ids, t_sub,
                                           pos, start)
            _, d_new = _apply_layer(draft, dp, db, ids, d_sub, pos,
                                    start)
            return (_splice_row(t_cache, t_new, rowidx),
                    _splice_row(d_cache, d_new, rowidx)), \
                t_logits[0, -1, :].astype(jnp.float32)

        return chunk

    def step_exec(self, S, C, eos_token_id=None):
        """AOT single speculative step over ``S`` slots (ledger kind
        ``spec_step``)."""
        end = -1 if eos_token_id is None else int(eos_token_id)
        key = self._key("step2", S, None, C, 1, 1, end)
        fn = self._build_step(S, C, end)
        return self._compile(key, "spec_step", fn, self.step_avals(S, C),
                             {"slots": S, "cache": C, "eos": end,
                              "gamma": self._gamma},
                             donate_argnums=(4,))

    def chunk_exec(self, S, T, C):
        """AOT joint prefill-chunk executable over ``S`` slots (ledger
        kind ``spec_chunk``)."""
        key = self._key("chunk2", S, T, C, None, None)
        fn = self._build_chunk(S, T, C)
        return self._compile(key, "spec_chunk", fn,
                             self.chunk_avals(S, T, C),
                             {"slots": S, "chunk": T, "cache": C,
                              "gamma": self._gamma},
                             donate_argnums=(4,))

    def step_avals(self, S, C):
        """Non-state avals of the speculative slot step (cache pair,
        cur, start, finished, active, pos, max_commit)."""
        caches = (self._slot_cache_avals(S, C),
                  self._slot_draft_cache_avals(S, C))
        return (caches,
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32))

    def chunk_avals(self, S, T, C):
        """Non-state avals of the single-row joint prefill-chunk
        program (cache pair, ids [1, T], start [1], row index, block
        position)."""
        caches = (self._slot_cache_avals(S, C),
                  self._slot_draft_cache_avals(S, C))
        return (caches,
                jax.ShapeDtypeStruct((1, T), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32))

    def _slot_draft_cache_avals(self, S, C):
        raw = jax.eval_shape(lambda: self._init_draft_cache_raw(S, C))
        return [tuple(jax.ShapeDtypeStruct(p.shape, p.dtype) for p in c)
                for c in raw]

    def slot_cache_avals_all(self, S, C):
        """The speculative step donates the (target, draft) cache PAIR —
        the KV data movers must pull/push both, or a restored session
        would decode against a stale draft cache and break acceptance."""
        return (self._slot_cache_avals(S, C),
                self._slot_draft_cache_avals(S, C))

    def init_slot_cache(self, S, C):
        """Zero (target, draft) cache pair for a fresh slot session."""
        t = super().init_slot_cache(S, C)
        raw = jax.eval_shape(lambda: self._init_draft_cache_raw(S, C))
        d = [tuple(jnp.zeros(tuple(p.shape), p.dtype) for p in c)
             for c in raw]
        return (t, d)

    # -- AOT compile + ledger ------------------------------------------------
    def _key(self, phase, B, P, C, steps, beam, end=None):
        return super()._key(phase, B, P, C, steps, beam, end) \
            + (("arg:gamma", self._gamma),)

    def _program_identity(self):
        # the joint program bakes the draft architecture too (its params
        # ride _state_avals already; the class/config pin the code path)
        d_cfg = getattr(self._draft, "config", None)
        d_cfg_r = repr(sorted(vars(d_cfg).items())) \
            if d_cfg is not None and hasattr(d_cfg, "__dict__") \
            else repr(d_cfg)
        return super()._program_identity() + (
            "draft", type(self._draft).__name__, d_cfg_r, self._gamma)

    def prefill_exec(self, B, P, C):
        key = self._key("prefill", B, P, C, None, None)
        fn = self._build_prefill(B, P, C)
        avals = (jax.ShapeDtypeStruct((B, P), jnp.int32),
                 jax.ShapeDtypeStruct((B,), jnp.int32))
        return self._compile(key, self._PREFILL_KIND, fn, avals,
                             {"batch": B, "prompt": P, "cache": C,
                              "gamma": self._gamma})

    def decode_exec(self, B, C, steps, beam=1, eos_token_id=None):
        end = -1 if eos_token_id is None else int(eos_token_id)
        key = self._key("decode", B, None, C, steps, beam, end)
        fn = self._build_decode(B, C, int(steps), int(beam), end)
        avals_of = lambda raw: [tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                                      for p in c) for c in raw]
        t_avals = avals_of(jax.eval_shape(
            lambda: self._init_cache_raw(B, C)))
        d_avals = avals_of(jax.eval_shape(
            lambda: self._init_draft_cache_raw(B, C)))
        vocab = self._vocab_size()
        avals = ((t_avals, d_avals),
                 jax.ShapeDtypeStruct((B, vocab), jnp.float32),
                 jax.ShapeDtypeStruct((B,), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32))
        return self._compile(key, self._DECODE_KIND, fn, avals,
                             {"batch": B, "cache": C, "steps": int(steps),
                              "beam": int(beam), "gamma": self._gamma})

    # -- execution ----------------------------------------------------------
    def decode(self, cache, logits0, start, pos0, steps, beam_size=1,
               eos_token_id=None):
        """Run (compiling if new) the speculative while-loop from a
        prefill result; returns tokens [B, steps] — bit-identical to the
        plain greedy decode of the target.  Publishes acceptance
        telemetry (counters + histogram + ``last_stats``)."""
        B = logits0.shape[0]
        C = cache[0][0][0].shape[2]
        ex = self.decode_exec(B, int(C), int(steps), int(beam_size),
                              eos_token_id)
        toks, accepted, proposed, nsteps = ex(
            *self._state_args(), cache,
            jnp.asarray(logits0, jnp.float32),
            jnp.asarray(start, jnp.int32), jnp.int32(pos0))
        a, p, s = int(accepted), int(proposed), int(nsteps)
        rate = a / p if p else 0.0
        SPEC_PROPOSED.labels(model=self._site).inc(p)
        SPEC_ACCEPTED.labels(model=self._site).inc(a)
        SPEC_ACCEPT_RATIO.labels(model=self._site).observe(rate)
        self.last_stats = {
            "gamma": self._gamma, "accepted": a, "proposed": p,
            "spec_steps": s, "acceptance_rate": round(rate, 4),
            "draft_fraction": round(self._draft_fraction, 4),
        }
        return toks

    def _annotate_decode_span(self, d, t1, t2, steps):
        """The speculative step is one device program: split the fenced
        decode window into estimated ``draft``/``verify`` child spans by
        the models' parameter-byte ratio and attach the measured
        acceptance stats, then the uniform per-token events."""
        st = self.last_stats or {}
        tm = t1 + (t2 - t1) * self._draft_fraction
        _tracing.child(d, "draft", t1, tm, estimated=True,
                       gamma=self._gamma, proposed=st.get("proposed"),
                       spec_steps=st.get("spec_steps"))
        _tracing.child(d, "verify", tm, t2, estimated=True,
                       accepted=st.get("accepted"),
                       acceptance_rate=st.get("acceptance_rate"))
        d.set_attr(gamma=self._gamma,
                   acceptance_rate=st.get("acceptance_rate"),
                   spec_steps=st.get("spec_steps"))
        super()._annotate_decode_span(d, t1, t2, steps)
