from .bert import BertModel, BertConfig, BertForPretraining  # noqa: F401
from .gpt import GPTModel, GPTConfig  # noqa: F401
from .gpt import GPTMoEModel, GPTMoEConfig  # noqa: F401
