"""BERT family — the framework's flagship transformer workload.

Capability parity target: BERT-base pretraining with fleet CollectiveOptimizer
is benchmark config 3 of BASELINE.json; the reference era trains it via
dist_transformer.py-style fixtures (python/paddle/fluid/tests/unittests/).
The model is built from the framework's own nn.TransformerEncoder
(nn/layer/transformer.py ≙ reference python/paddle/nn/layer/transformer.py).

TPU-first notes:
  * ``apply_tensor_parallel`` annotates Megatron-style shardings (column-
    parallel QKV/FFN-in, row-parallel out/FFN-out) — GSPMD inserts the
    all-reduces on ICI; no manual c_allreduce ops.
  * default dtype bf16-friendly: params stay fp32, compute casts via
    TrainStep(compute_dtype=bfloat16) (the AMP strategy).
"""
from __future__ import annotations

import dataclasses

from ... import nn
from ...nn import functional as F
from ...ops import manipulation as M


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    pad_token_id: int = 0

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def large(cls):
        return cls(hidden_size=1024, num_hidden_layers=24,
                   num_attention_heads=16, intermediate_size=4096)

    @classmethod
    def tiny(cls, vocab_size=128, hidden_size=32, layers=2, heads=2, seq=64):
        return cls(vocab_size=vocab_size, hidden_size=hidden_size,
                   num_hidden_layers=layers, num_attention_heads=heads,
                   intermediate_size=hidden_size * 4,
                   max_position_embeddings=seq)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ... import ops
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(seq_len, dtype="int64")
            position_ids = M.unsqueeze(position_ids, 0)
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig = None, with_pool=True, **kwargs):
        super().__init__()
        cfg = cfg or BertConfig(**kwargs)
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_hidden_layers)
        self.pooler = BertPooler(cfg) if with_pool else None

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        from ... import ops
        if attention_mask is not None:
            # [B, S] 1/0 mask -> additive [B, 1, 1, S]
            m = M.unsqueeze(attention_mask, [1, 2])
            attention_mask = (1.0 - m.astype("float32")) * -1e4
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(emb, attention_mask)
        if self.pooler is not None:
            return seq, self.pooler(seq)
        return seq


class BertPretrainingHeads(nn.Layer):
    def __init__(self, cfg: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.activation = getattr(F, cfg.hidden_act)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.decoder_weight = embedding_weights  # tied
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.seq_relationship = nn.Linear(cfg.hidden_size, 2)

    def forward(self, sequence_output, pooled_output, masked_positions=None):
        from ... import ops
        if masked_positions is not None:
            # gather ONLY the masked rows before the vocab projection (the
            # reference head's masked_positions gather): with ~15% masking
            # this cuts the 30k-vocab matmul + fp32 CE to the prediction
            # set. The gather is a one-hot MATMUL, not take_along_axis —
            # its backward is then also a matmul on the MXU instead of a
            # serialized TPU scatter.
            sel = F.one_hot(masked_positions,
                            sequence_output.shape[1]).astype(
                sequence_output.dtype)                    # [B, P, S]
            sequence_output = ops.matmul(sel, sequence_output)
        h = self.layer_norm(self.activation(self.transform(sequence_output)))
        logits = ops.matmul(h, self.decoder_weight, transpose_y=True) \
            + self.decoder_bias
        nsp = self.seq_relationship(pooled_output)
        return logits, nsp


class BertForPretraining(nn.Layer):
    """MLM + NSP pretraining wrapper; forward returns the combined loss when
    labels are given (the fused-loss layout keeps everything in one XLA
    computation)."""

    def __init__(self, cfg: BertConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or BertConfig(**kwargs)
        self.config = cfg
        self.bert = BertModel(cfg)
        self.cls = BertPretrainingHeads(
            cfg, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_label=None,
                masked_positions=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        logits, nsp = self.cls(seq, pooled, masked_positions)
        if masked_lm_labels is None:
            return logits, nsp
        mlm_loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            masked_lm_labels.reshape([-1]), ignore_index=-100)
        loss = mlm_loss
        if next_sentence_label is not None:
            loss = loss + F.cross_entropy(nsp,
                                          next_sentence_label.reshape([-1]))
        return loss


class BertMLMHead(nn.Layer):
    """MLM head producing the loss directly (pipeline tail stage).

    Untied from the word embedding: in the pipelined decomposition embed and
    head live in separate param groups, so the reference's tied
    decoder_weight (modeling's BertPretrainingHeads) becomes an independent
    decoder matrix — the standard trade when pipelining the reference model.
    """

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.activation = getattr(F, cfg.hidden_act)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.decoder = nn.Linear(cfg.hidden_size, cfg.vocab_size)

    def forward(self, sequence_output, masked_lm_labels=None):
        h = self.layer_norm(self.activation(self.transform(sequence_output)))
        logits = self.decoder(h)
        if masked_lm_labels is None:
            return logits
        return F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            masked_lm_labels.reshape([-1]), ignore_index=-100)


def build_pipeline_model(cfg: BertConfig = None, num_stages: int = None,
                         num_microbatches: int = 2, mesh=None):
    """BERT MLM as a PipelineModule: BertEmbeddings → encoder-layer trunk
    over the pp axis → BertMLMHead.  Train via
    TrainStep(module, opt)((input_ids,), labels) or
    fleet.distributed_optimizer with strategy.pipeline=True
    (≙ PipelineOptimizer's device_guard section split of this model,
    fluid/optimizer.py:3702)."""
    from ...parallel.pipeline import PipelineModule

    cfg = cfg or BertConfig.base()
    embed = BertEmbeddings(cfg)
    blocks = [nn.TransformerEncoderLayer(
        cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
        dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
        attn_dropout=cfg.attention_probs_dropout_prob, act_dropout=0.0)
        for _ in range(cfg.num_hidden_layers)]
    head = BertMLMHead(cfg)
    return PipelineModule(embed, blocks, head, num_stages=num_stages,
                          num_microbatches=num_microbatches, mesh=mesh)


def apply_tensor_parallel(model: BertModel):
    """Annotate Megatron-style TP shardings over the ``mp`` mesh axis.

    Column-parallel: q/k/v projections and FFN-in (output dim sharded);
    row-parallel: attention-out and FFN-out (input dim sharded); vocab
    embedding sharded on vocab. ≙ paddle.distributed.split's
    _parallel_linear/_parallel_embedding (collective.py:492,526) without the
    manual allreduce insertion.

    Rules-driven since ISSUE 9: the hand per-param shard_parameter list
    this function used to carry is now ONE table —
    ``analysis.autoshard.transformer_rules()`` — applied through the
    transform pass (verified bit-identical to the deleted hand layout;
    tests/test_autoshard.py keeps the control inline).  The plan's
    unmatched-leaf report must stay empty for the zoo.
    """
    from ...analysis.autoshard import apply as _autoshard_apply
    from ...analysis.autoshard import transformer_rules
    _autoshard_apply(model, rules=transformer_rules())
    return model
