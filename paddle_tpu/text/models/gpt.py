"""GPT-style decoder-only LM (causal transformer).

Not present in the 2.0-rc reference model zoo, but the natural second
transformer workload for the TPU framework (the scaling/pipeline strategies
need a decoder-only config). Shares TP annotation logic with bert.py.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from ... import nn
from ...nn import functional as F
from ...ops import manipulation as M


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    dropout: float = 0.1

    @classmethod
    def tiny(cls, vocab_size=128, hidden_size=32, layers=2, heads=2, seq=64):
        return cls(vocab_size=vocab_size, hidden_size=hidden_size,
                   num_layers=layers, num_heads=heads,
                   intermediate_size=hidden_size * 4,
                   max_position_embeddings=seq)


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or GPTConfig(**kwargs)
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.dropout, activation="gelu", normalize_before=True)
        self.encoder = nn.TransformerEncoder(layer, cfg.num_layers,
                                             norm=nn.LayerNorm(cfg.hidden_size))

    def forward(self, input_ids, labels=None):
        from ... import ops
        b, s = input_ids.shape
        pos = M.unsqueeze(ops.arange(s, dtype="int64"), 0)
        h = self.drop(self.wte(input_ids) + self.wpe(pos))
        causal = ops.triu(ops.full([s, s], -1e4, dtype="float32"), diagonal=1)
        h = self.encoder(h, M.unsqueeze(causal, [0, 1]))
        logits = ops.matmul(h, self.wte.weight, transpose_y=True)
        if labels is None:
            return logits
        return F.cross_entropy(
            logits[:, :-1].reshape([-1, self.config.vocab_size]),
            labels[:, 1:].reshape([-1]))

    # -- incremental decoding (static-shape KV ring cache) -------------------
    def init_cache(self, batch, max_len, dtype=None):
        """Per-layer zero ring caches [batch, heads, max_len, head_dim];
        ``max_len`` is the compile-time cache length."""
        if dtype is None:
            dtype = str(self.wte.weight.dtype)
        return self.encoder.gen_ring_cache(batch, max_len, dtype)

    def forward_cached(self, input_ids, cache, cache_position,
                       start_positions):
        """One incremental step over the ring cache.

        input_ids [B, T] — the tokens to append (the LEFT-padded prompt
        at prefill, one token per row at decode); ``cache_position`` is
        the cache column the first new token writes (int or traced int32
        scalar — the write wraps modulo the static cache length);
        ``start_positions`` [B] is each row's first valid cache column
        (its left-pad offset).  Token positions and the additive
        validity+causality mask are derived from those two, so batch and
        cache length stay compile-time constants.  Returns
        (logits [B, T, V], updated cache).
        """
        import jax.numpy as jnp
        from ... import ops
        from ...framework.tensor import Tensor, unwrap
        b, t = input_ids.shape
        C = cache[0].k.shape[2]
        pos = unwrap(cache_position)
        pos = jnp.asarray(pos, jnp.int32) if not isinstance(pos, int) \
            else jnp.int32(pos)
        start = jnp.asarray(unwrap(start_positions), jnp.int32)
        row = pos + jnp.arange(t, dtype=jnp.int32)       # global cache cols
        pos_ids = jnp.clip(row[None, :] - start[:, None], 0,
                           self.config.max_position_embeddings - 1)
        h = self.drop(self.wte(input_ids) + self.wpe(Tensor(pos_ids)))
        # valid key col j for query row i: start_b <= j <= pos + i
        col = jnp.arange(C, dtype=jnp.int32)
        valid = ((col[None, None, None, :] <= row[None, None, :, None])
                 & (col[None, None, None, :] >= start[:, None, None, None]))
        mask = Tensor(jnp.where(valid, 0.0, -1e30).astype(jnp.float32))
        window = None
        if t == 1:
            # decode step: the mask is a contiguous [start, pos+1) window,
            # which is what the flash-decoding kernel dispatches on
            window = (Tensor(start), Tensor(jnp.broadcast_to(pos + 1, (b,))))
        h, new_cache = self.encoder(
            h, mask, cache=cache,
            cache_position=Tensor(pos % jnp.int32(C)),
            decode_window=window)
        logits = ops.matmul(h, self.wte.weight, transpose_y=True)
        return logits, new_cache

    def generate(self, input_ids, lengths=None, max_new_tokens=32,
                 beam_size=1, eos_token_id=None, draft_model=None, **kw):
        """Autoregressive decoding compiled as exactly two executables
        (text.generation: one prefill jit + one scanned decode step).
        With ``draft_model`` (a smaller GPT over the same vocab) the two
        executables become the joint prefill + the speculative
        propose/verify scan (text.speculative) — same greedy output, up
        to gamma+1 tokens per target forward."""
        from ..generation import generate as _generate
        return _generate(self, input_ids, draft_model=draft_model,
                         lengths=lengths, max_new_tokens=max_new_tokens,
                         beam_size=beam_size, eos_token_id=eos_token_id,
                         **kw)


@dataclasses.dataclass
class GPTMoEConfig(GPTConfig):
    """GPT config with every ``moe_every``-th block's FFN replaced by an
    expert-parallel MoE layer (nn.layer.moe).  ``moe_top_k`` /
    ``moe_capacity_factor`` default to the FLAGS_moe_* values and are
    RESOLVED at model construction, so the config (and therefore the
    persistent executable cache's program-identity key, which hashes
    these fields) always names the concrete gating program."""

    moe_num_experts: int = 8
    moe_top_k: Optional[int] = None           # None -> FLAGS_moe_top_k
    moe_capacity_factor: Optional[float] = None  # None -> FLAGS value
    moe_every: int = 2                        # every other block is MoE
    moe_aux_weight: float = 1e-2

    @classmethod
    def tiny(cls, vocab_size=128, hidden_size=32, layers=2, heads=2,
             seq=64, experts=8, top_k=None, capacity_factor=None,
             moe_every=2):
        return cls(vocab_size=vocab_size, hidden_size=hidden_size,
                   num_layers=layers, num_heads=heads,
                   intermediate_size=hidden_size * 4,
                   max_position_embeddings=seq, moe_num_experts=experts,
                   moe_top_k=top_k, moe_capacity_factor=capacity_factor,
                   moe_every=moe_every)


class GPTMoEModel(GPTModel):
    """Decoder-only LM with alternating dense / Mixture-of-Experts
    blocks: block ``i`` is MoE when ``(i + 1) % moe_every == 0`` (so
    ``moe_every=2`` replaces every other block's FFN), expert FFNs are
    stacked ``[E, ...]`` parameters sharded over the expert-parallel
    axis, and the training loss carries the gates' load-balance aux
    term.  Shares GPTModel's incremental-decoding contract verbatim —
    ``generate()``, flash-decode and the serving decode grid run
    unchanged (the MoE dispatch is just more ops inside the same two
    executables).

    ``dispatch="dense"`` builds the bit-match control: identical
    parameters and gating, GShard dense-dispatch instead of the
    all-to-all movers.
    """

    def __init__(self, cfg: GPTMoEConfig = None, *, mesh=None,
                 dispatch: str = "routed", annotate: bool = True,
                 **kwargs):
        from ... import nn
        from ...nn.layer.moe import (MoEEncoderLayer, moe_capacity_factor,
                                     moe_top_k)
        nn.Layer.__init__(self)
        cfg = cfg or GPTMoEConfig(**kwargs)
        # resolve flag-defaulted gating knobs NOW: the config is the
        # program identity (persistent cache) and must be concrete
        if cfg.moe_top_k is None:
            cfg.moe_top_k = moe_top_k()
        if cfg.moe_capacity_factor is None:
            cfg.moe_capacity_factor = moe_capacity_factor()
        if cfg.moe_every < 1:
            raise ValueError(f"moe_every must be >= 1, got {cfg.moe_every}")
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        blocks = []
        for i in range(cfg.num_layers):
            if (i + 1) % cfg.moe_every == 0:
                blocks.append(MoEEncoderLayer(
                    cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
                    cfg.moe_num_experts, dropout=cfg.dropout,
                    activation="gelu", normalize_before=True,
                    top_k=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor, mesh=mesh,
                    dispatch=dispatch, annotate=annotate))
            else:
                blocks.append(nn.TransformerEncoderLayer(
                    cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
                    dropout=cfg.dropout, activation="gelu",
                    normalize_before=True))
        self.encoder = nn.TransformerEncoder(
            blocks, norm=nn.LayerNorm(cfg.hidden_size))

    def forward(self, input_ids, labels=None):
        from ...nn.layer.moe import total_aux_loss
        from ...framework.tensor import Tensor
        out = GPTModel.forward(self, input_ids, labels)
        if labels is None:
            return out
        # loss plumbing: the gates train through the aux term riding the
        # same scalar TrainStep already consumes
        aux = total_aux_loss(self)
        return out + Tensor(aux) * self.config.moe_aux_weight

    def moe_aux_loss(self):
        """Summed load-balance loss of the last forward (traced inside
        a step; concrete after an eager call — the bench probe)."""
        from ...nn.layer.moe import total_aux_loss
        return total_aux_loss(self)


def apply_tensor_parallel(model: GPTModel):
    """Megatron-style TP over ``mp`` for the decoder-only stack — the
    SAME ``analysis.autoshard.transformer_rules()`` table BERT shards
    from (vocab-sharded ``wte``, column-parallel QKV/FFN-in,
    row-parallel attn-out/FFN-out; ``wpe`` replicated).  GPT never had a
    hand annotation list: the table covered it from day one — the tied
    ``wte`` output projection rides the embedding's vocab shard."""
    from ...analysis.autoshard import apply as _autoshard_apply
    from ...analysis.autoshard import transformer_rules
    _autoshard_apply(model, rules=transformer_rules())
    return model
