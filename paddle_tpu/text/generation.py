"""Autoregressive decoding as a first-class workload: static-shape
KV-cache ``generate()`` compiled as exactly TWO executables.

The reference stack decodes through the contrib beam-search DSL
(incubate/decoder.py) — a host loop that re-dispatches per token and, on
a shape-keyed compiler, would recompile per token as the sequence grows.
The TPU-idiomatic form fixes every shape at compile time:

  * **ring KV cache** — per attention layer a ``(B, N, C, H)`` buffer
    written in place with ``lax.dynamic_update_slice`` at an explicit
    ``cache_position`` (nn/layer/transformer.py ``RingCache``); batch and
    cache length ``C`` are compile-time constants, validity is a mask;
  * **left-padded prompts** — prompts pad LEFT up to a prefill bucket
    ``P`` (FLAGS_decode_buckets), so every row's valid cache window is
    the contiguous ``[P - len_b, pos)`` and the last prefill column is
    the last prompt token for every row (no per-row gather);
  * **one prefill executable** per (batch, P, C): embeds the prompt,
    fills the cache, returns next-token logits;
  * **one decode executable** per (batch, C, steps, beam): the whole
    token loop is a single jitted ``lax.scan`` over the step body —
    greedy argmax, or beam search via ops.decode's ``beam_search_step`` +
    ``beam_parent_gather`` (the incubate BeamSearchDecoder reorder
    semantics) + ``gather_tree`` backtrace.

Every compile is recorded in the recompile ledger (site
``generate:<model>``, kinds ``generate_prefill`` / ``generate_decode``);
repeat calls at the same buckets are ledgered cache hits — the
zero-per-token-compile proof the tests and the serving engine assert.

Model contract: ``layer.init_cache(batch, max_len, dtype)`` and
``layer.forward_cached(input_ids, cache, cache_position,
start_positions)`` (text.models.gpt implements it over the ring-cache
transformer stack).
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework import core
from ..framework import flags as _flags
from ..framework.enforce import (InvalidArgumentError, OutOfRangeError,
                                 PreconditionNotMetError)
from ..framework.functional import _bound_state, layer_state
from ..framework.tensor import Tensor, unwrap
from ..ops.decode import (_beam_search_step_fn, _gather_tree_fn,
                          beam_parent_gather)
from ..profiler import ledger as _ledger
from ..profiler import tracing as _tracing
from ..serving.bucketing import BucketLadder

__all__ = ["Generator", "generate"]


def _aval(a):
    return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)


def _rebuild_ring(cache):
    """Raw plane tuples -> per-layer RingCache/QuantRingCache namedtuples
    (arity decides: 2 planes = bf16 rows, 4 = int8 rows + scale planes)."""
    from ..nn.layer.transformer import MultiHeadAttention as _MHA
    out = []
    for c in cache:
        cls = _MHA.RingCache if len(c) == 2 else _MHA.QuantRingCache
        out.append(cls(*(Tensor(p) for p in c)))
    return out


def _apply_layer(layer, params, buffers, ids, cache, pos, start):
    """Raw-array incremental forward of ONE model: bind the state
    snapshot into the live layer and run its forward_cached under
    no-grad (the @to_static pure-fn pattern, jit/__init__.py).  Shared
    by the Generator (target) and the speculative draft."""
    ring = _rebuild_ring(cache)
    with core.no_grad_guard(), _bound_state(layer, params, buffers):
        logits, new_cache = layer.forward_cached(
            Tensor(ids), ring, pos, Tensor(start))
    return unwrap(logits), [tuple(unwrap(p) for p in c) for c in new_cache]


def _slice_row(cache, rowidx):
    """Row ``rowidx`` of every cache plane as a batch-1 cache view (a
    traced ``dynamic_slice`` — the row index is a runtime scalar)."""
    return [tuple(lax.dynamic_slice(p, (rowidx,) + (0,) * (p.ndim - 1),
                                    (1,) + p.shape[1:]) for p in c)
            for c in cache]


def _splice_row(cache, sub, rowidx):
    """Write a batch-1 cache back into row ``rowidx`` of the full
    planes — the single-row inverse of :func:`_slice_row`."""
    return [tuple(lax.dynamic_update_slice(
                      p, ps, (rowidx,) + (0,) * (p.ndim - 1))
                  for p, ps in zip(c, cs))
            for c, cs in zip(cache, sub)]




class Generator:
    """Compiled incremental decoding for one model.

    Owns the model's functional state snapshot and a cache of AOT
    executables keyed on (phase, batch, prompt-bucket, cache-bucket,
    steps, beam) — the warm-up set the serving engine enumerates.  All
    compiles are ledgered at ``site``; hits at warmed keys are ledgered
    cache hits (the zero-steady-state-compile invariant).
    """

    def __init__(self, layer, site: Optional[str] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 max_len: Optional[int] = None, mesh=None,
                 param_specs=None):
        if not hasattr(layer, "forward_cached") \
                or not hasattr(layer, "init_cache"):
            raise InvalidArgumentError(
                f"{type(layer).__name__} does not implement the "
                "incremental-decoding contract (init_cache + "
                "forward_cached) — see text.models.GPTModel")
        if mesh is not None and type(self) is not Generator:
            raise InvalidArgumentError(
                "sharded decoding (mesh=) supports the plain Generator "
                f"only; {type(self).__name__} must run per-replica "
                "unsharded")
        layer.eval()
        self._layer = layer
        # sharded serving (serving/cluster): params placed per the
        # autoshard-derived specs, KV planes pinned to the cluster-wide
        # layout rule, all avals carrying shardings so the AOT programs
        # compile SPMD over the mesh.  mesh=None (the default) is the
        # single-device path, byte-identical to before.
        self._mesh = mesh
        self._param_specs = dict(param_specs or {})
        self._site = site or f"generate:{type(layer).__name__.lower()}"
        self._max_len = int(max_len if max_len is not None
                            else _flags.flag("decode_max_len"))
        spec = seq_buckets if seq_buckets is not None \
            else _flags.flag("decode_buckets")
        ladder = BucketLadder.from_flag(spec)
        # cache lengths cap at max_len; max_len itself is the top bucket
        self._seq_buckets = sorted(
            {b for b in ladder.buckets if b <= self._max_len}
            | {self._max_len})
        self._execs = {}
        self.refresh_state()

    @property
    def site(self):
        return self._site

    @property
    def seq_buckets(self):
        return list(self._seq_buckets)

    def refresh_state(self):
        """Re-snapshot params/buffers from the live layer (after training
        or loading).  Shapes are unchanged, so no recompile — the fresh
        arrays just flow through the existing executables."""
        self._params, self._buffers = layer_state(self._layer)
        if self._mesh is not None:
            self._params = {n: jax.device_put(
                v, self._sharding(self._param_specs.get(n)))
                for n, v in self._params.items()}
            self._buffers = {n: jax.device_put(v, self._sharding())
                             for n, v in self._buffers.items()}

    # -- sharded-serving layout (serving/cluster/sharding.py) ----------------
    def _sharding(self, spec=None):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self._mesh,
                             spec if spec is not None else P())

    def kv_plane_sharding(self, shape):
        """The pinned ring-plane sharding at this generator's mesh (None
        on the single-device path) — handoff ingest and the decode
        avals both consult it, so cross-pool layouts always agree."""
        if self._mesh is None:
            return None
        from ..serving.cluster.sharding import kv_plane_spec
        return self._sharding(kv_plane_spec(shape, self._mesh))

    # -- bucketing -----------------------------------------------------------
    def prefill_bucket(self, length: int) -> int:
        """Smallest sequence bucket holding ``length`` prompt tokens."""
        for b in self._seq_buckets:
            if length <= b:
                return b
        raise OutOfRangeError(
            f"prompt length {length} exceeds the largest decode bucket "
            f"{self._seq_buckets[-1]} (FLAGS_decode_buckets / "
            "FLAGS_decode_max_len)")

    def cache_bucket(self, prefill: int, steps: int) -> int:
        """Smallest sequence bucket holding prefill + generated tokens."""
        need = int(prefill) + int(steps)
        for b in self._seq_buckets:
            if need <= b:
                return b
        raise OutOfRangeError(
            f"prompt bucket {prefill} + {steps} new tokens = {need} "
            f"exceeds FLAGS_decode_max_len={self._max_len}")

    # -- the two pure programs ----------------------------------------------
    def _apply_cached(self, params, buffers, ids, cache, pos, start):
        return _apply_layer(self._layer, params, buffers, ids, cache, pos,
                            start)

    def _init_cache_raw(self, B, C):
        ring = self._layer.init_cache(B, C)
        return [tuple(unwrap(p) for p in c) for c in ring]

    def _build_prefill(self, B, P, C):
        def prefill(params, buffers, ids, start):
            cache0 = self._init_cache_raw(B, C)
            logits, cache = self._apply_cached(
                params, buffers, ids, cache0, jnp.int32(0), start)
            # left-padding: the last column is the last prompt token for
            # EVERY row — one static slice, no per-row gather
            return cache, logits[:, -1, :].astype(jnp.float32)
        return prefill

    def _build_decode(self, B, C, steps, beam, end):
        # end == -1 encodes "no eos": argmax tokens are always >= 0, so
        # the finished mask never trips and the one program serves both
        apply = self._apply_cached

        def greedy(params, buffers, cache, logits0, start, pos0):
            def step(carry, _):
                cache, logits, pos, finished = carry
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(finished, jnp.int32(end), tok)
                finished = finished | (tok == end)
                nlogits, ncache = apply(params, buffers, tok[:, None],
                                        cache, pos, start)
                return (ncache, nlogits[:, 0].astype(jnp.float32),
                        pos + 1, finished), tok

            init = (cache, logits0, pos0, jnp.zeros((B,), bool))
            _, toks = lax.scan(step, init, None, length=steps)
            return jnp.transpose(toks)                    # [B, steps]

        def beam_decode(params, buffers, cache, logits0, start, pos0):
            K = beam
            cache = [tuple(jnp.repeat(p, K, axis=0) for p in c)
                     for c in cache]
            start_k = jnp.repeat(start, K, axis=0)
            logp0 = jax.nn.log_softmax(logits0.astype(jnp.float32), axis=-1)
            V = logp0.shape[-1]
            # only beam 0 live at t=0 (the incubate BeamSearchDecoder
            # -inf init), so step 1 expands ONE beam
            scores0 = jnp.broadcast_to(
                jnp.where(jnp.arange(K) > 0, -1e9, 0.0), (B, K)
            ).astype(jnp.float32)
            logp0 = jnp.broadcast_to(logp0[:, None, :], (B, K, V))
            pre0 = jnp.full((B, K), end - 1, jnp.int32)   # != end: all live

            def step(carry, _):
                cache, pre_ids, scores, logp, pos = carry
                ids_t, scores_t, parents_t = _beam_search_step_fn(
                    pre_ids, scores, logp, beam_size=K, end_id=end,
                    is_accumulated=True)
                # reorder beam-parallel cache rows by the selected
                # parents — the incubate BeamSearchDecoder gather
                cache = [tuple(beam_parent_gather(p, parents_t) for p in c)
                         for c in cache]
                tok = ids_t.reshape(B * K)[:, None]
                nlogits, ncache = apply(params, buffers, tok, cache, pos,
                                        start_k)
                nlogp = jax.nn.log_softmax(
                    nlogits[:, 0].astype(jnp.float32), axis=-1
                ).reshape(B, K, V)
                return (ncache, ids_t, scores_t, nlogp, pos + 1), \
                    (ids_t, parents_t)

            init = (cache, pre0, scores0, logp0, pos0)
            (_, _, scores, _, _), (all_ids, all_parents) = lax.scan(
                step, init, None, length=steps)
            paths = _gather_tree_fn(all_ids, all_parents)  # [steps, B, K]
            return jnp.transpose(paths, (1, 2, 0)), scores

        return greedy if beam == 1 else beam_decode

    # -- slot-loop programs (serving/slots.py) -------------------------------
    def _build_step(self, S, C, end):
        """ONE greedy token step over ``S`` slot rows — the body of the
        run-to-completion scan, hoisted so the HOST owns the loop:
        requests retire/join between dispatches with no recompile and no
        cache copy.  Inactive rows' logits pass through unchanged so a
        freshly activated row is never clobbered; their CACHE write is
        deliberately NOT masked — the cache argument is donated and a
        per-row blend would force XLA to preserve the donated planes
        (a full-plane copy every step, measured ~4x the step cost on
        CPU).  Instead the host guarantees every column a step writes
        for an inactive row is dead: it lies inside the row's pending
        chunk window [act-Pb, act) and the slot loop dispatches chunk k
        only after the step at position act-n+k has retired (see
        slots._dispatch_chunks), so the chunk rewrite always lands
        after the last garbage write.  Emitted tokens for active rows
        are bit-identical to the scanned decode's per-row stream (row
        independence + the PR-7 batch/bucket invariance)."""
        apply = self._apply_cached

        def step(params, buffers, cache, logits, start, finished, active,
                 pos):
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            tok = jnp.where(finished, jnp.int32(end), tok)
            finished = finished | (tok == end)
            # inactive rows may carry garbage argmax (end == -1 included)
            # — clamp their fed token; their write lands in a dead column
            fed = jnp.where(active, tok, jnp.int32(0))
            nlogits, ncache = apply(params, buffers, fed[:, None], cache,
                                    pos, start)
            nlog = jnp.where(active[:, None],
                             nlogits[:, 0].astype(jnp.float32), logits)
            return ncache, nlog, finished, tok

        return step

    def _build_chunk(self, S, T, C):
        """One Sarathi-style prefill chunk: forward ``T`` prompt tokens
        of ONE joining row at the block position ``pos``, writing its
        K/V block without touching any other slot's plane.  The forward
        runs at batch 1 over the row's sliced planes — rows are
        independent in forward_cached, so the batch-1 compute is bit-
        identical to that row's lane in a batched dispatch, and a chunk
        costs the row's own FLOPs instead of ``S``× them.  Returns the
        chunk's last-column logits — the final chunk's are the
        activation logits (= the prefill executable's ``logits[:, -1]``
        for the same prompt)."""
        apply = self._apply_cached

        def chunk(params, buffers, cache, ids, start, rowidx, pos):
            sub = _slice_row(cache, rowidx)
            logits, nsub = apply(params, buffers, ids, sub, pos, start)
            return _splice_row(cache, nsub, rowidx), \
                logits[0, -1, :].astype(jnp.float32)

        return chunk

    def step_exec(self, S, C, eos_token_id=None):
        """AOT single-step decode executable over ``S`` slots at cache
        bucket ``C`` (ledger kind ``generate_step``) — the slot loop's
        hot dispatch."""
        if self._mesh is not None:
            raise InvalidArgumentError(
                "slot decode (FLAGS_decode_slots) runs per-replica "
                "unsharded — drop the mesh or the slot loop")
        end = -1 if eos_token_id is None else int(eos_token_id)
        key = self._key("step2", S, None, C, 1, 1, end)
        fn = self._build_step(S, C, end)
        return self._compile(key, "generate_step", fn,
                             self.step_avals(S, C),
                             {"slots": S, "cache": C, "eos": end},
                             donate_argnums=(2,))

    def chunk_exec(self, S, T, C):
        """AOT prefill-chunk executable over ``S`` slots at chunk width
        ``T`` and cache bucket ``C`` (ledger kind ``generate_chunk``)."""
        if self._mesh is not None:
            raise InvalidArgumentError(
                "slot decode (FLAGS_decode_slots) runs per-replica "
                "unsharded — drop the mesh or the slot loop")
        key = self._key("chunk2", S, T, C, None, None)
        fn = self._build_chunk(S, T, C)
        return self._compile(key, "generate_chunk", fn,
                             self.chunk_avals(S, T, C),
                             {"slots": S, "chunk": T, "cache": C},
                             donate_argnums=(2,))

    def step_avals(self, S, C):
        """Non-state avals of the slot step program (cache, logits,
        start, finished, active, pos) — shared by the AOT compile and
        the serving graph-lint admission gate."""
        vocab = self._vocab_size()
        return (self._slot_cache_avals(S, C),
                jax.ShapeDtypeStruct((S, vocab), jnp.float32),
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((), jnp.int32))

    def chunk_avals(self, S, T, C):
        """Non-state avals of the single-row prefill-chunk program
        (cache, ids [1, T], start [1], row index, block position)."""
        return (self._slot_cache_avals(S, C),
                jax.ShapeDtypeStruct((1, T), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32))

    def _slot_cache_avals(self, S, C):
        raw = jax.eval_shape(lambda: self._init_cache_raw(S, C))
        return [tuple(jax.ShapeDtypeStruct(p.shape, p.dtype) for p in c)
                for c in raw]

    def slot_cache_avals_all(self, S, C):
        """Abstract values of the FULL slot-cache tree the step program
        donates — every plane the KV data movers (pull/push below) must
        cover.  The speculative subclass widens this to its
        (target, draft) cache pair."""
        return self._slot_cache_avals(S, C)

    def _block_avals(self, S, T, C):
        """Avals of one T-column single-row block of the slot cache:
        every plane is 4-D with the column dim at axis 2 (bf16 k/v and
        int8 k/v + f32 scales alike), so a block is the same tree with
        shape (1, heads, T, head_dim-or-1)."""
        return jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(
                (1, p.shape[1], T, p.shape[3]), p.dtype),
            self.slot_cache_avals_all(S, C))

    # -- KV data movers (prefix/session cache, serving/prefix_cache.py +
    #    serving/sessions.py): pure cache-tree slicing programs, compiled
    #    once at SlotLoop construction like the step/chunk executables --
    def pull_block_exec(self, S, T, C):
        """AOT read of one T-column block of one slot row, every plane
        (ledger kind ``kv_pull_block``): ``(cache, rowidx, base) ->
        block tree``.  Read-only — the cache is NOT donated, so the live
        session planes stay valid; the returned block is the device
        segment the prefix cache publishes."""
        if self._mesh is not None:
            raise InvalidArgumentError(
                "the prefix/session KV cache runs per-replica unsharded "
                "(FLAGS_decode_slots) — drop the mesh")
        key = self._key("pull_block", S, T, C, None, None)

        def pull(cache, rowidx, base):
            zero = jnp.int32(0)
            return jax.tree_util.tree_map(
                lambda p: lax.dynamic_slice(
                    p, (rowidx, zero, base, zero),
                    (1, p.shape[1], T, p.shape[3])), cache)

        avals = (self.slot_cache_avals_all(S, C),
                 jax.ShapeDtypeStruct((), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32))
        return self._compile_data(key, "kv_pull_block", pull, avals,
                                  {"slots": S, "chunk": T, "cache": C})

    def push_block_exec(self, S, T, C):
        """AOT write of one T-column block into one slot row, every
        plane (ledger kind ``kv_push_block``): ``(cache, block, rowidx,
        base) -> cache``.  The cache is donated exactly like the step
        program's, so a restore is an in-place column write, not a
        full-plane copy; the block argument is not donated and stays
        valid (a pinned prefix block can restore into many rows)."""
        if self._mesh is not None:
            raise InvalidArgumentError(
                "the prefix/session KV cache runs per-replica unsharded "
                "(FLAGS_decode_slots) — drop the mesh")
        key = self._key("push_block", S, T, C, None, None)

        def push(cache, block, rowidx, base):
            zero = jnp.int32(0)
            return jax.tree_util.tree_map(
                lambda p, b: lax.dynamic_update_slice(
                    p, b, (rowidx, zero, base, zero)), cache, block)

        avals = (self.slot_cache_avals_all(S, C),
                 self._block_avals(S, T, C),
                 jax.ShapeDtypeStruct((), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32))
        return self._compile_data(key, "kv_push_block", push, avals,
                                  {"slots": S, "chunk": T, "cache": C},
                                  donate_argnums=(0,))

    def pull_row_exec(self, S, C):
        """AOT read of one slot row's FULL-width planes (ledger kind
        ``kv_pull_row``): ``(cache, rowidx) -> row tree``.  One dispatch
        per session park — the host slices the validity window
        ``[start, pos)`` out of the fetched row."""
        if self._mesh is not None:
            raise InvalidArgumentError(
                "the prefix/session KV cache runs per-replica unsharded "
                "(FLAGS_decode_slots) — drop the mesh")
        key = self._key("pull_row", S, None, C, None, None)

        def pull(cache, rowidx):
            zero = jnp.int32(0)
            return jax.tree_util.tree_map(
                lambda p: lax.dynamic_slice(
                    p, (rowidx, zero, zero, zero),
                    (1,) + tuple(p.shape[1:])), cache)

        avals = (self.slot_cache_avals_all(S, C),
                 jax.ShapeDtypeStruct((), jnp.int32))
        return self._compile_data(key, "kv_pull_row", pull, avals,
                                  {"slots": S, "cache": C})

    def init_slot_cache(self, S, C):
        """Zero device planes for a fresh slot session — never compiled
        as a program of its own (validity windows make the init values
        unobservable; zeros match the in-graph prefill init)."""
        raw = jax.eval_shape(lambda: self._init_cache_raw(S, C))
        return [tuple(jnp.zeros(tuple(p.shape), p.dtype) for p in c)
                for c in raw]

    # -- AOT compile + ledger ------------------------------------------------
    def _key(self, phase, B, P, C, steps, beam, end=None):
        # the cache storage dtype is part of the program: flipping
        # FLAGS_kv_cache_dtype recompiles (ledgered, loud under
        # serving_strict) instead of silently serving stale planes
        kv = str(_flags.flag("kv_cache_dtype")).lower()
        return tuple([("arg:phase", phase), ("arg:batch", B),
                      ("arg:kv", kv)]
                     + ([("arg:mesh", self._mesh_label())]
                        if self._mesh is not None else [])
                     + ([("arg:prompt", P)] if P is not None else [])
                     + [("arg:cache", C)]
                     + ([("arg:steps", steps), ("arg:beam", beam),
                         ("arg:eos", end)]
                        if steps is not None else []))

    def _mesh_label(self):
        if self._mesh is None:
            return ""
        return "x".join(f"{a}{n}" for a, n in dict(self._mesh.shape).items())

    def _state_avals(self):
        """Avals of the leading state arguments every generate program
        takes (params, buffers) — the speculative subclass appends the
        draft model's pair.  Under a mesh the avals carry the param
        shardings, so the AOT programs lower SPMD."""
        if self._mesh is not None:
            return ({n: jax.ShapeDtypeStruct(
                        tuple(a.shape), a.dtype,
                        sharding=self._sharding(self._param_specs.get(n)))
                     for n, a in self._params.items()},
                    {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype,
                                             sharding=self._sharding())
                     for n, a in self._buffers.items()})
        return (jax.tree_util.tree_map(_aval, self._params),
                jax.tree_util.tree_map(_aval, self._buffers))

    def _state_args(self):
        return (self._params, self._buffers)

    def _program_identity(self):
        """Restart-stable architecture identity for the persistent
        executable cache: layer class + config + state avals.  Weights
        are runtime arguments, so two processes decoding the same
        architecture share executables regardless of parameter values —
        the cold host compiles the grid, every warm host loads it."""
        cfg = getattr(self._layer, "config", None)
        cfg_r = repr(sorted(vars(cfg).items())) \
            if cfg is not None and hasattr(cfg, "__dict__") else repr(cfg)
        avals = jax.tree_util.tree_map(
            lambda a: (tuple(a.shape), str(a.dtype)), self._state_avals())
        mesh_id = () if self._mesh is None else (
            self._mesh_label(),
            tuple(sorted((n, repr(s))
                         for n, s in self._param_specs.items())))
        return ("generator", type(self._layer).__name__, cfg_r,
                repr(avals), self._max_len, tuple(self._seq_buckets),
                *mesh_id)

    def _compile(self, key, kind, fn, arg_avals, extra,
                 out_shardings=None, donate_argnums=None):
        ex = self._execs.get(key)
        if ex is not None:
            _ledger.record_cache_hit(self._site)
            return ex
        from ..jit import persistent_cache as _pcache
        jit_kw = {} if out_shardings is None \
            else {"out_shardings": out_shardings}
        if donate_argnums is not None:
            # slot-loop programs donate the ring cache: XLA aliases the
            # input planes to the output planes, turning the per-step
            # column writes into in-place updates instead of full-plane
            # copies (the host never reuses the donated handle)
            jit_kw["donate_argnums"] = donate_argnums
        ex, _loaded = _pcache.load_or_compile(
            lambda: jax.jit(fn, **jit_kw).lower(*self._state_avals(),
                                                *arg_avals).compile(),
            site=self._site, kind=kind, key=key,
            extra_key=self._program_identity(), extra=extra)
        self._execs[key] = ex
        return ex

    def _compile_data(self, key, kind, fn, arg_avals, extra,
                      donate_argnums=None):
        """`_compile` for pure data-mover programs (the KV pull/push
        executables): no model-state avals are prepended, so the program
        is a function of the cache tree alone and its persistent-cache
        identity is still keyed on `_program_identity()` (the cache
        layout derives from the architecture)."""
        ex = self._execs.get(key)
        if ex is not None:
            _ledger.record_cache_hit(self._site)
            return ex
        from ..jit import persistent_cache as _pcache
        jit_kw = {}
        if donate_argnums is not None:
            jit_kw["donate_argnums"] = donate_argnums
        ex, _loaded = _pcache.load_or_compile(
            lambda: jax.jit(fn, **jit_kw).lower(*arg_avals).compile(),
            site=self._site, kind=kind, key=key,
            extra_key=self._program_identity(), extra=extra)
        self._execs[key] = ex
        return ex

    def is_compiled(self, phase, B, P=None, C=None, steps=None,
                    beam=1, eos_token_id=None) -> bool:
        if steps is None:
            return self._key(phase, B, P, C, None, None) in self._execs
        end = -1 if eos_token_id is None else int(eos_token_id)
        return self._key(phase, B, P, C, steps, beam, end) in self._execs

    def prefill_exec(self, B, P, C):
        key = self._key("prefill", B, P, C, None, None)
        fn = self._build_prefill(B, P, C)
        out_sh = None
        if self._mesh is not None:
            repl = self._sharding()
            avals = (jax.ShapeDtypeStruct((B, P), jnp.int32, sharding=repl),
                     jax.ShapeDtypeStruct((B,), jnp.int32, sharding=repl))
            # pin the cache output planes to the cluster-wide KV layout
            # (and the logits replicated) so the decode executable — and
            # a decode POOL in another process — ingests without guessing
            shapes = jax.eval_shape(lambda: self._init_cache_raw(B, C))
            out_sh = ([tuple(self.kv_plane_sharding(p.shape) for p in c)
                       for c in shapes], repl)
        else:
            avals = (jax.ShapeDtypeStruct((B, P), jnp.int32),
                     jax.ShapeDtypeStruct((B,), jnp.int32))
        return self._compile(key, "generate_prefill", fn, avals,
                             {"batch": B, "prompt": P, "cache": C},
                             out_shardings=out_sh)

    def decode_exec(self, B, C, steps, beam=1, eos_token_id=None):
        end = -1 if eos_token_id is None else int(eos_token_id)
        key = self._key("decode", B, None, C, steps, beam, end)
        fn = self._build_decode(B, C, int(steps), int(beam), end)
        # the decode program's cache avals are exactly the prefill
        # program's cache outputs — derive them abstractly
        cache_avals = jax.eval_shape(lambda: self._init_cache_raw(B, C))
        if self._mesh is not None:
            repl = self._sharding()
            cache_avals = [tuple(jax.ShapeDtypeStruct(
                                     p.shape, p.dtype,
                                     sharding=self.kv_plane_sharding(
                                         p.shape))
                                 for p in c)
                           for c in cache_avals]
            vocab = self._vocab_size()
            avals = (cache_avals,
                     jax.ShapeDtypeStruct((B, vocab), jnp.float32,
                                          sharding=repl),
                     jax.ShapeDtypeStruct((B,), jnp.int32, sharding=repl),
                     jax.ShapeDtypeStruct((), jnp.int32, sharding=repl))
            return self._compile(key, "generate_decode", fn, avals,
                                 {"batch": B, "cache": C,
                                  "steps": int(steps), "beam": int(beam)},
                                 out_shardings=repl)
        cache_avals = [tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                             for p in c)
                       for c in cache_avals]
        vocab = self._vocab_size()
        avals = (cache_avals,
                 jax.ShapeDtypeStruct((B, vocab), jnp.float32),
                 jax.ShapeDtypeStruct((B,), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32))
        return self._compile(key, "generate_decode", fn, avals,
                             {"batch": B, "cache": C, "steps": int(steps),
                              "beam": int(beam)})

    def _vocab_size(self):
        cfg = getattr(self._layer, "config", None)
        v = getattr(cfg, "vocab_size", None)
        if v is None:
            raise PreconditionNotMetError(
                "cannot infer vocab size for the decode executable; the "
                "layer must expose config.vocab_size")
        return int(v)

    # -- the two phases, executed --------------------------------------------
    def prefill(self, ids, start, cache_len):
        """Run (compiling if new) the prefill executable on LEFT-padded
        int32 prompts ``ids [B, P]`` with per-row pad offsets ``start
        [B]``; returns (device cache, next-token logits [B, V])."""
        if self._mesh is not None:
            # host arrays: the SPMD executable places them per its own
            # (replicated) input shardings — a pre-committed single-
            # device array would be a layout mismatch
            ids = np.asarray(ids, np.int32)
            B, P = ids.shape
            ex = self.prefill_exec(B, P, int(cache_len))
            return ex(*self._state_args(), ids,
                      np.asarray(start, np.int32))
        ids = jnp.asarray(ids, jnp.int32)
        B, P = ids.shape
        ex = self.prefill_exec(B, P, int(cache_len))
        return ex(*self._state_args(), ids,
                  jnp.asarray(start, jnp.int32))

    def decode(self, cache, logits0, start, pos0, steps, beam_size=1,
               eos_token_id=None):
        """Run (compiling if new) the scanned decode executable from a
        prefill result.  Greedy returns tokens [B, steps]; beam returns
        (ids [B, K, steps], scores [B, K])."""
        B = logits0.shape[0]
        C = cache[0][0].shape[2]
        ex = self.decode_exec(B, int(C), int(steps), int(beam_size),
                              eos_token_id)
        if self._mesh is not None:
            return ex(*self._state_args(), cache,
                      np.asarray(logits0, np.float32),
                      np.asarray(start, np.int32), np.int32(pos0))
        return ex(*self._state_args(), cache,
                  jnp.asarray(logits0, jnp.float32),
                  jnp.asarray(start, jnp.int32), jnp.int32(pos0))

    # -- host-side prep + the public call ------------------------------------
    def pack_prompts(self, prompts, bucket):
        """LEFT-pad variable-length int prompts to [rows, bucket]; returns
        (ids int32, start int32 [rows]) — start[b] = bucket - len_b is
        row b's first valid cache column."""
        rows = len(prompts)
        ids = np.zeros((rows, bucket), np.int32)
        start = np.empty((rows,), np.int32)
        for i, p in enumerate(prompts):
            p = np.asarray(p).reshape(-1).astype(np.int32)
            if p.size == 0:
                raise InvalidArgumentError("empty prompt (0 tokens)")
            if p.size > bucket:
                raise OutOfRangeError(
                    f"prompt of {p.size} tokens exceeds bucket {bucket}")
            ids[i, bucket - p.size:] = p
            start[i] = bucket - p.size
        return ids, start

    def generate(self, input_ids, lengths=None, max_new_tokens=32,
                 beam_size=1, eos_token_id=None):
        """Greedy/beam decoding of a batch of prompts.

        ``input_ids`` [B, L] (right-padded; ``lengths`` [B] gives true
        prompt lengths, default L).  Exactly two executables run: the
        (batch, prompt-bucket, cache-bucket) prefill and the (batch,
        cache-bucket, steps, beam) decode scan.  Greedy returns a Tensor
        of generated ids [B, max_new_tokens]; beam returns (ids
        [B, beam, max_new_tokens], scores [B, beam]) Tensors.
        """
        ids_np = np.asarray(unwrap(input_ids))
        if ids_np.ndim != 2:
            raise InvalidArgumentError(
                f"input_ids must be [batch, length], got {ids_np.shape}")
        B, L = ids_np.shape
        steps = int(max_new_tokens)
        if steps < 1:
            raise InvalidArgumentError("max_new_tokens must be >= 1")
        lens = np.full((B,), L, np.int64) if lengths is None \
            else np.asarray(unwrap(lengths)).reshape(-1).astype(np.int64)
        if lens.shape[0] != B or (lens < 1).any() or (lens > L).any():
            raise InvalidArgumentError(
                f"lengths must be [batch] in [1, {L}], got {lens}")
        max_pos = getattr(getattr(self._layer, "config", None),
                          "max_position_embeddings", None)
        if max_pos is not None and int(lens.max()) + steps > int(max_pos):
            raise OutOfRangeError(
                f"prompt ({int(lens.max())}) + max_new_tokens ({steps}) "
                f"exceeds max_position_embeddings={max_pos}")
        P = self.prefill_bucket(int(lens.max()))
        C = self.cache_bucket(P, steps)
        prompts = [ids_np[b, :lens[b]] for b in range(B)]
        ids, start = self.pack_prompts(prompts, P)
        tr = _tracing.start_span("generate", model=self._site, rows=B,
                                 steps=steps, beam=beam_size)
        if tr is None:                     # off-path: one branch, no fence
            cache, logits0 = self.prefill(ids, start, C)
            out = self.decode(cache, logits0, start, P, steps,
                              beam_size=beam_size,
                              eos_token_id=eos_token_id)
        else:
            # traced call: fence at the scan boundary so the
            # prefill/decode split (and the per-token attribution across
            # the scanned token loop) is honest device time; any compile
            # the call pays lands on this span via the ledger hook
            with _tracing.use_span(tr):
                t0 = time.monotonic()
                cache, logits0 = self.prefill(ids, start, C)
                jax.block_until_ready(logits0)
                t1 = time.monotonic()
                _tracing.child(tr, "prefill", t0, t1, prompt_bucket=P,
                               cache_bucket=C)
                out = self.decode(cache, logits0, start, P, steps,
                                  beam_size=beam_size,
                                  eos_token_id=eos_token_id)
                jax.block_until_ready(out)
                t2 = time.monotonic()
            dt = (t2 - t1) / steps
            d = _tracing.start_span("decode", parent=tr, t0=t1,
                                    steps=steps, cache_bucket=C,
                                    per_token_ms=round(dt * 1e3, 4))
            if d is not None:
                self._annotate_decode_span(d, t1, t2, steps)
                _tracing.finish(d, end=t2)
            _tracing.finish(tr, end=t2)
        if beam_size == 1:
            return Tensor(out)
        paths, scores = out
        return Tensor(paths), Tensor(scores)

    def _annotate_decode_span(self, d, t1, t2, steps):
        """Fill the traced decode span: one event per generated token,
        spread uniformly across the fenced scan window (the token loop
        is ONE device program; the host never observes token k alone).
        The speculative subclass adds draft/verify children here."""
        dt = (t2 - t1) / steps
        for k in range(steps):
            d.event("token", t=t1 + (k + 1) * dt, index=k)

    __call__ = generate


def generate(layer, input_ids, draft_model=None, **kwargs):
    """Module-level convenience: (build and memoize a Generator on the
    layer, then) decode.  With ``draft_model`` (a second, smaller layer
    implementing the same init_cache/forward_cached contract) the call
    runs draft/target speculative decoding instead — bit-identical
    greedy output at up to gamma+1 tokens per target forward.  See
    :class:`Generator` / text.speculative.SpeculativeGenerator."""
    if draft_model is not None:
        from .speculative import SpeculativeGenerator
        gen = getattr(layer, "_paddle_tpu_spec_generator", None)
        if gen is None or gen._layer is not layer \
                or gen._draft is not draft_model:
            gen = SpeculativeGenerator(layer, draft_model)
            layer._paddle_tpu_spec_generator = gen
        else:
            gen.refresh_state()      # pick up trained/loaded weights
        return gen.generate(input_ids, **kwargs)
    gen = getattr(layer, "_paddle_tpu_generator", None)
    if gen is None or gen._layer is not layer:
        gen = Generator(layer)
        layer._paddle_tpu_generator = gen
    else:
        gen.refresh_state()          # pick up trained/loaded weights
    return gen.generate(input_ids, **kwargs)
