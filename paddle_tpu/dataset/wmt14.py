"""dataset/wmt14.py parity: train/test readers of
(src_ids, trg_ids, trg_ids_next)."""
__all__ = ["train", "test", "fetch"]


def _reader(mode, dict_size):
    from ..text.datasets import WMT14
    ds = WMT14(mode=mode, dict_size=dict_size)

    def reader():
        for i in range(len(ds)):
            s, t, tn = ds[i]
            yield list(s), list(t), list(tn)
    return reader


def train(dict_size=30000):
    return _reader("train", dict_size)


def test(dict_size=30000):
    return _reader("test", dict_size)


def fetch():
    """No-op (zero-egress)."""
