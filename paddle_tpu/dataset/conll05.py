"""dataset/conll05.py parity: the SRL test reader + dict accessors."""
__all__ = ["get_dict", "test", "fetch"]

_CACHE = {}


def _ds():
    if "ds" not in _CACHE:
        from ..text.datasets import Conll05st
        _CACHE["ds"] = Conll05st()
    return _CACHE["ds"]


def get_dict():
    return _ds().get_dict()


def test():
    ds = _ds()

    def reader():
        for i in range(len(ds)):
            yield tuple(ds[i])
    return reader


def fetch():
    """No-op (zero-egress)."""
