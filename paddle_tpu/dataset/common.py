"""dataset/common.py parity: the shared cache-home + md5/download hooks.

Zero-egress container: ``download`` refuses (datasets read local files or
synthesize); DATA_HOME matches the vision/text loaders' cache root.
"""
import hashlib
import os

DATA_HOME = os.environ.get("PADDLE_TPU_DATA_HOME",
                           os.path.expanduser("~/.cache/paddle_tpu/datasets"))

__all__ = ["DATA_HOME", "md5file", "download"]


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    raise RuntimeError(
        "paddle_tpu datasets never download implicitly (zero-egress "
        f"container); place the file for {module_name!r} under DATA_HOME "
        f"({DATA_HOME}) or pass an explicit data_file path to the 2.0 "
        "dataset class")


def _reader_from(dataset):
    """Adapt a 2.0 map-style Dataset to a legacy reader creator."""
    def reader():
        for i in range(len(dataset)):
            item = dataset[i]
            yield tuple(item) if isinstance(item, (tuple, list)) else (item,)
    return reader
