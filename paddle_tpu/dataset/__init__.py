"""paddle.dataset parity: the legacy reader-creator API.

Reference: python/paddle/dataset/ — per-corpus modules exposing
``train()``/``test()`` reader creators (zero-arg callables yielding
sample tuples).  Each delegates to the 2.0 dataset classes
(vision/datasets, text/datasets), which parse the reference record
formats from local files and fall back to deterministic synthetic data
(zero-egress container policy); ``fetch()`` is therefore a no-op hook.
"""
from . import (  # noqa: F401
    mnist, cifar, imdb, imikolov, movielens, uci_housing, wmt14, wmt16,
    conll05, flowers, voc2012, common,
)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "movielens",
           "uci_housing", "wmt14", "wmt16", "conll05", "flowers",
           "voc2012", "common"]
