"""dataset/imdb.py parity: word_dict() builds the vocabulary;
train(word_idx)/test(word_idx) yield (doc ids, label) ENCODED WITH THE
SUPPLIED DICT (the reference encodes raw text against word_idx; here the
2.0 dataset's internal encoding is re-mapped through it)."""
__all__ = ["train", "test", "word_dict", "fetch"]

_CACHE = {}


def _ds(mode, data_file=None, cutoff=150):
    key = (mode, data_file, cutoff)
    if key not in _CACHE:
        from ..text.datasets import Imdb
        _CACHE[key] = Imdb(data_file=data_file, mode=mode, cutoff=cutoff)
    return _CACHE[key]


def word_dict(data_file=None, cutoff=150):
    return _ds("train", data_file, cutoff).word_idx


def _reader(mode, word_idx, data_file, cutoff):
    ds = _ds(mode, data_file, cutoff)

    def encode(doc):
        if word_idx is None or word_idx == ds.word_idx:
            return list(doc)
        # re-map the dataset's internal ids through the caller's dict
        inv = {i: w for w, i in ds.word_idx.items()}
        unk = word_idx.get("<unk>", len(word_idx) - 1)
        return [word_idx.get(inv.get(int(i), "<unk>"), unk) for i in doc]

    def reader():
        for i in range(len(ds)):
            doc, label = ds[i]
            yield encode(doc), int(label[0])
    return reader


def train(word_idx=None, data_file=None, cutoff=150):
    return _reader("train", word_idx, data_file, cutoff)


def test(word_idx=None, data_file=None, cutoff=150):
    return _reader("test", word_idx, data_file, cutoff)


def fetch():
    """No-op (zero-egress)."""
