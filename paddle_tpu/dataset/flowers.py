"""dataset/flowers.py parity: train/valid/test image readers with the
reference's mapper/xmap plumbing."""
from .common import _reader_from

__all__ = ["train", "valid", "test", "fetch"]


def _reader(mode, mapper, buffered_size, use_xmap):
    from ..vision.datasets import Flowers
    base = _reader_from(Flowers(mode=mode))
    if mapper is None:
        return base
    from ..reader import xmap_readers, map_readers
    if use_xmap:
        return xmap_readers(mapper, base, 4, buffered_size, order=True)
    return map_readers(lambda sample: mapper(sample), base)


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("train", mapper, buffered_size, use_xmap)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("valid", mapper, buffered_size, use_xmap)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("test", mapper, buffered_size, use_xmap)


def fetch():
    """No-op (zero-egress)."""
