"""dataset/wmt16.py parity: train/test readers of
(src_ids, trg_ids, trg_ids_next)."""
__all__ = ["train", "test", "fetch"]


def _reader(mode, dict_size):
    from ..text.datasets import WMT16
    ds = WMT16(mode=mode, src_dict_size=dict_size,
               trg_dict_size=dict_size)

    def reader():
        for i in range(len(ds)):
            s, t, tn = ds[i]
            yield list(s), list(t), list(tn)
    return reader


def train(src_dict_size=30000, trg_dict_size=30000, src_lang="en"):
    return _reader("train", src_dict_size)


def test(src_dict_size=30000, trg_dict_size=30000, src_lang="en"):
    return _reader("test", src_dict_size)


def fetch():
    """No-op (zero-egress)."""
