"""dataset/cifar.py parity: train10/test10/train100/test100 readers
yielding (image[3072] f32 in [0,1]-ish, label int)."""
from .common import _reader_from

__all__ = ["train10", "test10", "train100", "test100", "fetch"]


def _ds(cls, mode):
    base = cls(mode=mode)

    class Flat:
        def __len__(self):
            return len(base)

        def __getitem__(self, i):
            img, label = base[i]
            return img.reshape(-1).astype("float32"), int(label)
    return Flat()


def train10():
    from ..vision.datasets import Cifar10
    return _reader_from(_ds(Cifar10, "train"))


def test10():
    from ..vision.datasets import Cifar10
    return _reader_from(_ds(Cifar10, "test"))


def train100():
    from ..vision.datasets import Cifar100
    return _reader_from(_ds(Cifar100, "train"))


def test100():
    from ..vision.datasets import Cifar100
    return _reader_from(_ds(Cifar100, "test"))


def fetch():
    """No-op (zero-egress)."""
