"""dataset/voc2012.py parity: segmentation (image, mask) readers."""
from .common import _reader_from

__all__ = ["train", "val", "test", "fetch"]


def _reader(mode):
    from ..vision.datasets import VOC2012
    return _reader_from(VOC2012(mode=mode))


def train():
    return _reader("train")


def val():
    return _reader("valid")


def test():
    return _reader("test")


def fetch():
    """No-op (zero-egress)."""
