"""dataset/mnist.py parity: train()/test() yield (image[784] f32 in
[-1,1], label int) — the reference's flattened record contract."""
from .common import _reader_from

__all__ = ["train", "test", "fetch"]


def _ds(mode):
    from ..vision.datasets import MNIST
    base = MNIST(mode=mode)

    class Flat:
        def __len__(self):
            return len(base)

        def __getitem__(self, i):
            img, label = base[i]
            return img.reshape(-1).astype("float32"), int(label)
    return Flat()


def train():
    return _reader_from(_ds("train"))


def test():
    return _reader_from(_ds("test"))


def fetch():
    """No-op (zero-egress; see common.download)."""
