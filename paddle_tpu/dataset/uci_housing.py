"""dataset/uci_housing.py parity: train()/test() yield
(features[13] f32, target[1] f32)."""
from .common import _reader_from

__all__ = ["train", "test", "fetch"]


def train(data_file=None):
    from ..text.datasets import UCIHousing
    return _reader_from(UCIHousing(data_file=data_file, mode="train"))


def test(data_file=None):
    from ..text.datasets import UCIHousing
    return _reader_from(UCIHousing(data_file=data_file, mode="test"))


def fetch():
    """No-op (zero-egress)."""
