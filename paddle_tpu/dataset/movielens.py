"""dataset/movielens.py parity: train/test record readers + metadata
accessors (max ids, categories/title dicts)."""
__all__ = ["train", "test", "get_movie_title_dict", "movie_categories",
           "max_movie_id", "max_user_id", "max_job_id", "age_table",
           "fetch"]

age_table = [1, 18, 25, 35, 45, 50, 56]

_CACHE = {}


def _ds(mode):
    if mode not in _CACHE:
        from ..text.datasets import Movielens
        _CACHE[mode] = Movielens(mode=mode)
    return _CACHE[mode]


def _reader(mode):
    ds = _ds(mode)

    def reader():
        for i in range(len(ds)):
            yield tuple(ds[i])
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def get_movie_title_dict():
    return _ds("train").movie_title_dict


def movie_categories():
    return _ds("train").categories_dict


def max_movie_id():
    return max(_ds("train").movie_info)


def max_user_id():
    return max(_ds("train").user_info)


def max_job_id():
    return max(u.job_id for u in _ds("train").user_info.values())


def fetch():
    """No-op (zero-egress)."""
