"""dataset/imikolov.py parity: build_dict(min_word_freq) + train/test
(word_idx, n) N-gram readers; a supplied word_idx re-encodes the ids."""
__all__ = ["build_dict", "train", "test", "fetch"]

_CACHE = {}


def _ds(mode, n, data_type="NGRAM", min_word_freq=1):
    key = (mode, n, data_type, min_word_freq)
    if key not in _CACHE:
        from ..text.datasets import Imikolov
        _CACHE[key] = Imikolov(data_type=data_type, window_size=n,
                               mode=mode, min_word_freq=min_word_freq)
    return _CACHE[key]


def build_dict(min_word_freq=50):
    return _ds("train", 2, min_word_freq=min_word_freq).word_idx


def _reader(mode, word_idx, n, data_type):
    ds = _ds(mode, n, data_type)

    def encode(ids):
        if word_idx is None or word_idx == ds.word_idx:
            return tuple(ids)
        inv = {i: w for w, i in ds.word_idx.items()}
        unk = word_idx.get("<unk>", len(word_idx) - 1)
        return tuple(word_idx.get(inv.get(int(i), "<unk>"), unk)
                     for i in ids)

    def reader():
        for i in range(len(ds)):
            item = ds[i]
            if data_type == "NGRAM":
                yield encode(item)
            else:
                yield tuple(encode(part) for part in item)
    return reader


def train(word_idx=None, n=2, data_type="NGRAM"):
    return _reader("train", word_idx, n, data_type)


def test(word_idx=None, n=2, data_type="NGRAM"):
    return _reader("test", word_idx, n, data_type)


def fetch():
    """No-op (zero-egress)."""
