"""Industrial file-based datasets: InMemoryDataset / QueueDataset.

Reference parity: paddle/fluid/framework/data_set.h:43 (DatasetImpl,
GlobalShuffle :205), data_feed.h:305 (InMemoryDataFeed/MultiSlotDataFeed),
data_feed.proto (MultiSlotDesc: slot name/type/is_dense/shape), and the
Python wrappers python/paddle/distributed/fleet/dataset/dataset.py
(DatasetBase/InMemoryDataset/QueueDataset) + fluid DatasetFactory.

The MultiSlot text format, per line, slot-by-slot in declared order:
``<n> v1 ... vn`` — n values for that slot (uint64 ids for sparse slots,
floats for dense ones).

TPU-shape: the parsed records batch into feed dicts that feed
``Executor.train_from_dataset`` (the lax.scan epoch) and the PS trainer —
host-side Python/numpy does the parsing (the reference's parsing threads
are C++ for Python-2-era speed; numpy vectorized parsing holds the same
role here), while the chip consumes one pre-stacked epoch.

Global shuffle redistributes records PEER-TO-PEER (data_set.cc
GlobalShuffle parity: trainers send record batches to each other over
RPC): every worker runs a lightweight exchange server, endpoints
rendezvous through the fleet TCP store, and the buckets travel
worker→worker directly — the store carries only O(world) metadata
(endpoints + barrier keys), never the records, so the shuffle scales
with the slowest LINK instead of funneling the whole dataset through
one store socket.
"""
from __future__ import annotations

import os
import pickle
import subprocess
import threading
from typing import List, Optional

import numpy as np


class _ShuffleExchange:
    """Per-process record-exchange server for global_shuffle: accepts
    (tag, src, blob) deliveries from peer workers (the worker→worker RPC
    leg of data_set.cc GlobalShuffle; message framing shared with
    ps/service.py).  Tags scope deliveries to one shuffle round, so an
    early sender from the next round can never pollute this one.

    Hardening: the socket binds to THIS worker's interface (the
    PADDLE_CURRENT_ENDPOINT host) rather than 0.0.0.0, and every
    delivery must carry an HMAC-SHA256 over the blob keyed by the
    per-round secret distributed through the fleet store rendezvous —
    a blob is never unpickled before its MAC verifies, so a stranger on
    the network cannot inject records (or pickles) into the shuffle."""

    def __init__(self):
        import socket
        from .ps.service import _send_msg, _recv_msg
        self._send_msg, self._recv_msg = _send_msg, _recv_msg
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        local_only = bool(os.getenv("PADDLE_TPU_SHUFFLE_LOCAL"))
        if local_only:
            # loopback bind must advertise loopback — anything else points
            # peers at an address this socket does not listen on
            host = "127.0.0.1"
        else:
            # advertise THIS worker's real host: the launchers communicate
            # it via PADDLE_CURRENT_ENDPOINT (fleet/launch.py); POD_IP and
            # loopback are fallbacks for hand-rolled single-host setups
            cur = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
            host = cur.rsplit(":", 1)[0] if ":" in cur else \
                os.getenv("POD_IP", "127.0.0.1")
        try:
            # bind the advertised interface only — not every interface
            self._sock.bind((host, 0))
        except OSError:
            # the advertised name may not resolve to a local interface
            # (NAT / container port-maps): fall back to wildcard but keep
            # advertising the routable name
            self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(64)
        self.endpoint = f"{host}:{self._sock.getsockname()[1]}"
        self._cv = threading.Condition()
        self._inbox: dict = {}       # tag -> [records...]
        self._got: dict = {}         # tag -> count of deliveries
        self._want: dict = {}        # tag -> expected deliveries
        self._keys: dict = {}        # tag -> round HMAC key (bytes)
        self._dead: "collections.deque" = __import__(
            "collections").deque(maxlen=64)   # discarded round tags
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        import hmac as _hmac
        import hashlib
        try:
            msg = self._recv_msg(conn)
            if msg is None:
                return
            with self._cv:
                if msg.get("tag") in self._dead:
                    # a straggler delivering for an aborted round must not
                    # re-create the inbox discard() just cleaned
                    self._send_msg(conn, {"ok": True, "stale": True})
                    return
                key = self._keys.get(msg.get("tag"))
            if key is None:
                # expect() always precedes endpoint publication, so a
                # legitimate peer can never beat the key registration
                self._send_msg(conn, {"ok": False, "err": "unknown round"})
                return
            want = _hmac.new(key, msg.get("blob", b""),
                             hashlib.sha256).digest()
            if not _hmac.compare_digest(want, msg.get("mac", b"")):
                self._send_msg(conn, {"ok": False, "err": "bad mac"})
                return
            # only now is the blob trusted enough to unpickle
            records = pickle.loads(msg["blob"])
            with self._cv:
                if msg["tag"] in self._dead:
                    self._send_msg(conn, {"ok": True, "stale": True})
                    return
                self._inbox.setdefault(msg["tag"], []).extend(records)
                self._got[msg["tag"]] = self._got.get(msg["tag"], 0) + 1
                self._cv.notify_all()
            self._send_msg(conn, {"ok": True})
        finally:
            conn.close()

    def expect(self, tag, n_deliveries, key):
        with self._cv:
            self._want[tag] = n_deliveries
            self._keys[tag] = key

    def collect(self, tag, timeout=300.0):
        import time
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._got.get(tag, 0) < self._want.get(tag, 0):
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"global_shuffle: got {self._got.get(tag, 0)}/"
                        f"{self._want.get(tag, 0)} peer deliveries for "
                        f"round {tag}")
                self._cv.wait(left)
            out = self._inbox.pop(tag, [])
            self._got.pop(tag, None)
            self._want.pop(tag, None)
            self._keys.pop(tag, None)
        return out

    def discard(self, tag):
        """Drop all state for an aborted round — peers' deliveries must
        not pin a shard's worth of records in the process-lifetime
        singleton when a round fails (elastic retries re-shuffle under a
        fresh tag).  The tag joins a dead-list so a straggler delivering
        AFTER this cleanup is rejected instead of re-creating the inbox."""
        with self._cv:
            self._dead.append(tag)
            self._inbox.pop(tag, None)
            self._got.pop(tag, None)
            self._want.pop(tag, None)
            self._keys.pop(tag, None)


_exchange_singleton: List[Optional[_ShuffleExchange]] = [None]
_round_lock = threading.Lock()
_round_counter = [0]


def _shuffle_exchange() -> _ShuffleExchange:
    if _exchange_singleton[0] is None:
        _exchange_singleton[0] = _ShuffleExchange()
    return _exchange_singleton[0]


def _next_shuffle_round() -> int:
    """Process-wide monotonic round id: two datasets shuffling in one
    process (train + eval) must never share a tag/prefix — per-instance
    counters would both start at 0 and cross-pollute inboxes.  All
    workers shuffle the same datasets in the same program order, so the
    counter agrees across the gang."""
    with _round_lock:
        _round_counter[0] += 1
        return _round_counter[0]


def _ship_bucket(endpoint, tag, src, records, key):
    import hmac as _hmac
    import hashlib
    import socket
    from .ps.service import _send_msg, _recv_msg
    host, port = endpoint.rsplit(":", 1)
    blob = pickle.dumps(records, protocol=pickle.HIGHEST_PROTOCOL)
    mac = _hmac.new(key, blob, hashlib.sha256).digest()
    with socket.create_connection((host, int(port)), timeout=60) as s:
        _send_msg(s, {"tag": tag, "src": src, "blob": blob, "mac": mac})
        out = _recv_msg(s)
    if out is None or not out.get("ok"):
        raise RuntimeError(
            f"shuffle delivery to {endpoint} failed"
            f"{': ' + out['err'] if out and 'err' in out else ''}")

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


class _Slot:
    __slots__ = ("name", "dtype", "is_dense", "shape")

    def __init__(self, name, dtype="uint64", is_dense=False, shape=(1,)):
        self.name = name
        self.dtype = dtype
        self.is_dense = is_dense
        self.shape = tuple(shape)


class DatasetBase:
    """dataset.py DatasetBase parity: slot/file/batch configuration."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.pipe_command = "cat"
        self.use_var_names: List[str] = []
        self._slots: List[_Slot] = []
        self.queue_num = None
        self.drop_last = False

    # -- 2.0 style ----------------------------------------------------------
    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command="cat",
             input_type=0, fs_name="", fs_ugi="", download_cmd="cat",
             queue_num=None, **kwargs):
        self.set_batch_size(batch_size)
        self.set_thread(thread_num)
        if use_var:
            self.set_use_var(use_var)
        self.set_pipe_command(pipe_command)
        self.queue_num = queue_num
        return self

    # -- fluid setters ------------------------------------------------------
    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def set_use_var(self, var_list):
        """Declare the slots from static Variables (name/dtype/shape/
        lod_level) or plain names (sparse uint64 slots)."""
        self.use_var_names = []
        self._slots = []
        for v in var_list:
            if isinstance(v, str):
                self.use_var_names.append(v)
                self._slots.append(_Slot(v))
                continue
            name = v.name
            dtype = str(getattr(v, "dtype", "int64") or "int64")
            lod = getattr(v, "lod_level", 0)
            dense = (lod == 0 and "float" in dtype)
            shape = [d for d in (getattr(v, "shape", None) or [1])
                     if d not in (None, -1)]
            self.use_var_names.append(name)
            self._slots.append(_Slot(
                name, "float" if "float" in dtype else "uint64",
                is_dense=dense, shape=shape or (1,)))
        return self

    def set_slots(self, slots):
        """Explicit slot config: [{'name','type','is_dense','shape'}, ...]
        (data_feed.proto MultiSlotDesc analogue)."""
        self._slots = [_Slot(s["name"], s.get("type", "uint64"),
                             s.get("is_dense", False),
                             s.get("shape", (1,))) for s in slots]
        self.use_var_names = [s.name for s in self._slots]
        return self

    # -- parsing ------------------------------------------------------------
    def _read_lines(self, path):
        if self.pipe_command and self.pipe_command != "cat":
            # pipe_command parity: each file streams through the user's
            # preprocessor (data_feed.h pipe reader)
            proc = subprocess.Popen(
                f"{self.pipe_command} < {path}", shell=True,
                stdout=subprocess.PIPE, text=True)
            for line in proc.stdout:
                yield line
            proc.wait()
        else:
            with open(path) as f:
                yield from f

    def _parse_file(self, path):
        """One MultiSlot text file -> list of records
        (record = tuple of np arrays, one per slot in declared order)."""
        if not self._slots:
            raise ValueError("no slots declared: call set_use_var / "
                             "set_slots before loading")
        records = []
        for line in self._read_lines(path):
            toks = line.split()
            if not toks:
                continue
            pos = 0
            rec = []
            for slot in self._slots:
                n = int(toks[pos])
                pos += 1
                vals = toks[pos:pos + n]
                pos += n
                if slot.dtype == "float":
                    rec.append(np.asarray(vals, np.float32))
                else:
                    rec.append(np.asarray(vals, np.int64))
            records.append(tuple(rec))
        return records

    def _parse_all(self, filelist):
        """Multi-threaded parse (data_set.cc CreateReaders thread pool)."""
        if len(filelist) <= 1 or self.thread_num <= 1:
            out = []
            for p in filelist:
                out.extend(self._parse_file(p))
            return out
        results = [None] * len(filelist)

        def work(i, p):
            results[i] = self._parse_file(p)

        threads = []
        for i, p in enumerate(filelist):
            t = threading.Thread(target=work, args=(i, p), daemon=True)
            t.start()
            threads.append(t)
            while len([x for x in threads if x.is_alive()]) >= self.thread_num:
                threads[0].join(0.01)
                threads = [x for x in threads if x.is_alive()]
        for t in threads:
            t.join()
        out = []
        for r in results:
            out.extend(r or [])
        return out

    # -- batching -----------------------------------------------------------
    def _batches_from(self, records):
        """Yield feed dicts {slot_name: ndarray}. Sparse slots with equal
        per-record counts stack densely; ragged ones pad and add a
        ``<name>.lens`` entry (the lengths-based LoD carrier)."""
        B = self.batch_size
        for i in range(0, len(records), B):
            chunk = records[i:i + B]
            if len(chunk) < B and self.drop_last:
                continue
            feed = {}
            for si, slot in enumerate(self._slots):
                cols = [r[si] for r in chunk]
                lens = [len(c) for c in cols]
                if slot.is_dense or len(set(lens)) == 1:
                    feed[slot.name] = np.stack(cols)
                else:
                    m = max(lens)
                    pad = np.zeros((len(chunk), m), cols[0].dtype)
                    for j, c in enumerate(cols):
                        pad[j, :len(c)] = c
                    feed[slot.name] = pad
                    feed[slot.name + ".lens"] = np.asarray(lens, np.int64)
            yield feed


class InMemoryDataset(DatasetBase):
    """data_set.h DatasetImpl<InMemoryDataFeed> parity: load, shuffle
    (locally or across the fleet), iterate."""

    def __init__(self):
        super().__init__()
        self._records: List[tuple] = []
        self._loaded = False
        self._preload_thread: Optional[threading.Thread] = None
        self._seed: Optional[int] = None     # None = unseeded; 0 is a seed

    # -- loading ------------------------------------------------------------
    def load_into_memory(self):
        self._records = self._parse_all(self.filelist)
        self._loaded = True

    def preload_into_memory(self, thread_num=None):
        if thread_num:
            self.set_thread(thread_num)
        self._preload_thread = threading.Thread(
            target=self.load_into_memory, daemon=True)
        self._preload_thread.start()

    def wait_preload_done(self):
        if self._preload_thread is not None:
            self._preload_thread.join()
            self._preload_thread = None

    def release_memory(self):
        self._records = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        n = len(self._records)
        if fleet is not None:
            return int(fleet.util.all_reduce(np.asarray(n), "sum"))
        return n

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    # -- shuffling ----------------------------------------------------------
    def set_shuffle_seed(self, seed):
        self._seed = int(seed)

    def local_shuffle(self):
        rng = np.random.RandomState(self._seed)
        rng.shuffle(self._records)

    _EXCHANGE_TIMEOUT = 300.0

    def global_shuffle(self, fleet=None, thread_num=12):
        """DatasetImpl::GlobalShuffle (data_set.cc:205): redistribute
        records across all workers by hash — PEER-TO-PEER, as the
        reference sends record batches trainer→trainer over RPC.  The
        fleet TCP store carries only endpoints and barriers (O(world)
        metadata); record bytes travel on direct worker sockets."""
        self.local_shuffle()
        if fleet is None:
            return
        # accept the fleet module facade or a Fleet instance
        if not hasattr(fleet, "_role_maker") and hasattr(fleet, "_fleet"):
            fleet = fleet._fleet
        rm = fleet._role_maker
        world = fleet.worker_num()
        me = fleet.worker_index()
        if world <= 1:
            return
        store = rm._ensure_store()
        # per-worker stream: identical seeds across workers would correlate
        # the destination pattern and skew the redistribution
        base = 0 if self._seed is None else self._seed
        rng = np.random.RandomState(base + 12345 + me * 9973)
        dest = rng.randint(0, world, size=len(self._records))
        buckets = [[] for _ in range(world)]
        for r, d in zip(self._records, dest):
            buckets[d].append(r)
        # round scoping: restart generation (a store surviving an elastic
        # gang restart must never serve the dead gang's buckets) × a
        # process-wide monotonic round id (two datasets shuffling in one
        # process must not share a tag)
        rgen = store._restart_generation()
        gen = _next_shuffle_round()
        pre = f"__gshuf/{rgen}/{gen}"
        tag = f"{rgen}/{gen}"

        srv = _shuffle_exchange()
        try:
            # per-round delivery key, derived at the fleet-store
            # rendezvous: worker 0 mints it, everyone reads it through
            # the store before publishing an endpoint — so every
            # delivery a worker can receive is HMAC-checkable, and the
            # store itself still carries only O(world) metadata
            if me == 0:
                import secrets as _secrets
                store.set(f"{pre}/key", _secrets.token_hex(16).encode())
            round_key = store.get(f"{pre}/key")
            srv.expect(tag, world - 1, round_key)
            store.set(f"{pre}/ep/{me}", srv.endpoint.encode())
            store.barrier(f"{pre}/ep", world)
            eps = {d: store.get(f"{pre}/ep/{d}").decode()
                   for d in range(world) if d != me}

            # ship each outgoing bucket directly to its owner (parallel
            # senders ≙ the reference's send_request_table thread pool)
            errs = []

            def ship(d):
                try:
                    _ship_bucket(eps[d], tag, me, buckets[d], round_key)
                except Exception as e:       # surfaced after join
                    errs.append((d, e))

            senders = [threading.Thread(target=ship, args=(d,),
                                        daemon=True) for d in eps]
            for t in senders:
                t.start()
            for t in senders:
                t.join()
            if errs:
                raise RuntimeError(
                    f"global_shuffle: peer sends failed: {errs}")

            mine = list(buckets[me])
            mine.extend(srv.collect(tag, timeout=self._EXCHANGE_TIMEOUT))
        except BaseException:
            # aborted round: peers' deliveries must not leak in the
            # process-lifetime inbox
            srv.discard(tag)
            raise
        rng2 = np.random.RandomState(base + 777 + me)
        rng2.shuffle(mine)
        self._records = mine
        # everyone holds their records before anyone proceeds/cleans up
        store.barrier(f"{pre}/done", world)
        if me == 0:
            store.delete_prefix(pre + "/")

    # -- iteration ----------------------------------------------------------
    def __iter__(self):
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        return self._batches_from(self._records)

    def __len__(self):
        B = self.batch_size
        n = len(self._records)
        return n // B if self.drop_last else (n + B - 1) // B


class QueueDataset(DatasetBase):
    """data_set.h DatasetImpl<MultiSlotDataFeed> parity: streaming reads,
    no memory residency, no shuffle (the reference raises the same way)."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams from files; local_shuffle is only "
            "supported by InMemoryDataset (data_set.cc parity)")

    def global_shuffle(self, fleet=None, thread_num=12):
        raise NotImplementedError(
            "QueueDataset streams from files without memory residency, so "
            "there is nothing host-side to redistribute; the reference's "
            "queue-feed global shuffle happens on the PS side of its "
            "pipeline, a stage this design deliberately keeps out of the "
            "data path (records go file→feed→chip). Pre-shard the FILE "
            "LIST across workers (set_filelist with per-worker splits) "
            "for the same statistical effect, or use InMemoryDataset for "
            "true record-level global shuffle (data_set.cc parity)")

    def __iter__(self):
        def gen():
            buf = []
            for path in self.filelist:
                buf.extend(self._parse_file(path))
                while len(buf) >= self.batch_size:
                    yield next(iter(self._batches_from(
                        buf[:self.batch_size])))
                    buf = buf[self.batch_size:]
            if buf and not self.drop_last:
                yield next(iter(self._batches_from(buf)))
        return gen()
