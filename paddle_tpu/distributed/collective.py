"""Collective communication ops.

Reference parity: python/paddle/distributed/collective.py (all_reduce :157,
broadcast, all_gather, scatter, barrier) and the C++ collective op family
(paddle/fluid/operators/collective/: c_allreduce_op.h:157 ncclAllReduce,
c_broadcast, c_allgather, c_reducescatter, send_v2/recv_v2, alltoall).

TPU-native semantics: a collective is *communication inside a compiled SPMD
program*.  Inside a traced region whose mesh axis is bound (shard_map /
pjit-manual), these functions lower straight to XLA collectives on ICI
(lax.psum / all_gather / ppermute / all_to_all) — the ring_id of the
reference becomes the mesh axis name carried by the Group.  Called eagerly
in a single-process world they are the identity (world_size==1 per process),
matching the reference's behavior for nranks==1
(collective.py:190 returns early).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..parallel.mesh import get_mesh, DP_AXIS


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator: set of ranks + the mesh axis its collectives ride.

    ≙ ring_id → NCCLComm of collective_helper.h:63; here the "comm" is just
    the axis name resolved inside the compiled program.
    """

    def __init__(self, ranks: Optional[List[int]] = None, axis: str = DP_AXIS,
                 gid: int = 0):
        self.ranks = ranks
        self.axis = axis
        self.id = gid

    @property
    def nranks(self):
        if self.ranks is not None:
            return len(self.ranks)
        return get_mesh().shape.get(self.axis, 1)

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if self.ranks else rank

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis}, ranks={self.ranks})"


_default_group = Group(axis=DP_AXIS, gid=0)
_groups = {0: _default_group}
_next_gid = [1]


def new_group(ranks=None, backend=None, axis: str = None):
    """c_comm_init / paddle.distributed.new_group parity: register a
    communicator.  ``axis`` names the mesh axis the group's collectives use
    (defaults to dp)."""
    gid = _next_gid[0]
    _next_gid[0] += 1
    g = Group(ranks=list(ranks) if ranks else None,
              axis=axis or DP_AXIS, gid=gid)
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    return _groups.get(gid, _default_group)


def _axis_bound(axis: str) -> bool:
    """True if we're inside a traced region with this named axis bound.

    Only the unbound-axis signal (NameError from ``lax.axis_index``; jax also
    uses KeyError for unknown axis names in some resolution paths) routes to
    the eager no-op branch.  Any other exception under a bound axis is a real
    failure and must propagate — a bare ``except Exception`` here would turn
    collectives into silent identities inside traced regions.
    """
    try:
        lax.axis_index(axis)
        return True
    except (NameError, KeyError):
        return False


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _rewrap(x, out):
    if isinstance(x, Tensor):
        x._value = out
        return x
    return Tensor(out)


def _is_subgroup(g: Group) -> bool:
    """True if g.ranks is a proper subset of its mesh axis."""
    if g.ranks is None:
        return False
    axis_size = get_mesh().shape.get(g.axis, 1)
    return len(g.ranks) < axis_size


def _member_mask(g: Group):
    """Bool scalar (traced): is this rank a member of the group?"""
    idx = lax.axis_index(g.axis)
    return jnp.isin(idx, jnp.asarray(g.ranks, jnp.int32))


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    """c_allreduce_{sum,max,min,prod} (collective/c_allreduce_op.h).

    Subgroups (new_group(ranks=...) covering a proper subset of the axis)
    are honored by masking non-members with the reduction identity before
    the axis-wide collective — members get the ring-scoped result the
    reference's per-ring c_allreduce computes; values on non-member ranks
    are undefined there and here come out as the subgroup result.
    """
    g = group or _default_group
    x = _unwrap(tensor)
    if _axis_bound(g.axis):
        sub = _is_subgroup(g)
        if sub:
            member = _member_mask(g)
            if op in (ReduceOp.MAX, ReduceOp.MIN):
                # reduction identities in the tensor's OWN dtype (float
                # ±inf / integer iinfo bounds) — no promotion through
                # float32, which would corrupt int values above 2^24
                if jnp.issubdtype(x.dtype, jnp.floating):
                    lo, hi = -jnp.inf, jnp.inf
                else:
                    info = jnp.iinfo(x.dtype)
                    lo, hi = info.min, info.max
                lo = jnp.asarray(lo, x.dtype)
                hi = jnp.asarray(hi, x.dtype)
        if op == ReduceOp.SUM:
            out = lax.psum(jnp.where(member, x, 0) if sub else x, g.axis)
        elif op == ReduceOp.MAX:
            out = lax.pmax(jnp.where(member, x, lo) if sub else x, g.axis)
        elif op == ReduceOp.MIN:
            out = lax.pmin(jnp.where(member, x, hi) if sub else x, g.axis)
        elif op == ReduceOp.AVG:
            if sub:
                out = lax.psum(jnp.where(member, x, 0), g.axis) / len(g.ranks)
            else:
                out = lax.pmean(x, g.axis)
        elif op == ReduceOp.PROD:
            # no native product-reduce in XLA collectives; gather then
            # multiply (log/exp would NaN on non-positive inputs)
            xg = jnp.where(member, x, jnp.ones_like(x)) if sub else x
            out = jnp.prod(lax.all_gather(xg, g.axis), axis=0)
        else:
            raise ValueError(f"unknown ReduceOp {op}")
    else:
        out = x  # single-rank world: identity (collective.py:190 parity)
    return _rewrap(tensor, out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """c_reduce_*: allreduce then keep on dst (XLA has no rooted reduce;
    GSPMD would DCE the unused replicas)."""
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """c_allgather (collective/c_allgather_op.cc): concat along dim 0."""
    g = group or _default_group
    x = _unwrap(tensor)
    if _axis_bound(g.axis):
        if _is_subgroup(g):
            raise NotImplementedError(
                "all_gather over a proper subgroup of a mesh axis is not "
                "supported; create the group over a dedicated mesh axis "
                "(new_group(axis=...)) so the collective is ring-scoped")
        gathered = lax.all_gather(x, g.axis)  # [n, ...]
        n = gathered.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(gathered[i]) for i in range(n))
        return Tensor(gathered.reshape((-1,) + x.shape[1:]))
    if isinstance(tensor_list, list):
        tensor_list.append(Tensor(x))
    return Tensor(x)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """c_reducescatter: psum_scatter along dim 0."""
    g = group or _default_group
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        x = jnp.concatenate([_unwrap(t) for t in src], axis=0)
    else:
        x = _unwrap(src)
    if _axis_bound(g.axis):
        if _is_subgroup(g):
            raise NotImplementedError(
                "reduce_scatter over a proper subgroup of a mesh axis is not "
                "supported; use a dedicated mesh axis for the group")
        out = lax.psum_scatter(x, g.axis, scatter_dimension=0, tiled=True)
    else:
        out = x
    return _rewrap(tensor, out)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """c_broadcast: inside SPMD all replicas already hold src's value after
    the compiler inserts the collective; expressed as select + psum so the
    data provably originates from ``src``."""
    g = group or _default_group
    x = _unwrap(tensor)
    if _axis_bound(g.axis):
        idx = lax.axis_index(g.axis)
        # src is the GLOBAL rank (= axis index), for full-axis groups and
        # subgroups alike; only the src rank contributes to the psum, so a
        # subgroup broadcast is naturally ring-scoped.
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        out = lax.psum(masked, g.axis)
    else:
        out = x
    return _rewrap(tensor, out)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """c_scatter: src rank's list is distributed; SPMD form = dynamic slice
    of the (replicated) stacked input by axis index."""
    g = group or _default_group
    if tensor_list:
        stacked = jnp.stack([_unwrap(t) for t in tensor_list])
    else:
        stacked = _unwrap(tensor)[None]
    if _axis_bound(g.axis):
        idx = lax.axis_index(g.axis)
        out = lax.dynamic_index_in_dim(stacked, idx, keepdims=False)
    else:
        out = stacked[0]
    return _rewrap(tensor, out)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """AllToAll (Ulysses-style sequence exchange rides this)."""
    g = group or _default_group
    if isinstance(in_tensor_list, (list, tuple)):
        x = jnp.stack([_unwrap(t) for t in in_tensor_list])
    else:
        x = _unwrap(in_tensor_list)
    if _axis_bound(g.axis):
        out = lax.all_to_all(x, g.axis, split_axis=0, concat_axis=0,
                             tiled=False)
    else:
        out = x
    outs = [Tensor(out[i]) for i in range(out.shape[0])]
    if isinstance(out_tensor_list, list):
        out_tensor_list.extend(outs)
    return outs


def send_recv(tensor, src, dst, group=None):
    """Matched point-to-point pair as ONE collective-permute: the value held
    by ``src`` lands on ``dst`` (others receive zeros).  This is the XLA form
    of a send_v2/recv_v2 pair — both sides of the exchange must be in the
    same compiled program."""
    g = group or _default_group
    x = _unwrap(tensor)
    if _axis_bound(g.axis):
        return Tensor(lax.ppermute(x, g.axis, [(src, dst)]))
    return Tensor(x)


def shift(tensor, offset=1, group=None):
    """Uniform ring shift by ``offset`` (rank i → rank i+offset): the SPMD
    translation of the pipeline boundary pattern where every stage sends to
    the next and receives from the previous (optimizer.py:4178's
    send_v2/recv_v2 insertion).  Used by parallel.pipeline."""
    g = group or _default_group
    x = _unwrap(tensor)
    if _axis_bound(g.axis):
        n = g.nranks
        perm = [(i, (i + offset) % n) for i in range(n)]
        return Tensor(lax.ppermute(x, g.axis, perm))
    return Tensor(x)


def send(tensor, dst=0, group=None, sync_op=True):
    """send_v2 parity. XLA has no one-sided send: inside a traced SPMD region
    a send must be matched with its recv as one collective-permute — call
    ``send_recv(t, src, dst)`` or ``shift(t, offset)`` instead.  Eagerly in a
    1-rank world this is the identity (reference returns early for
    nranks==1)."""
    g = group or _default_group
    if _axis_bound(g.axis):
        raise RuntimeError(
            "one-sided send() cannot be expressed inside a compiled SPMD "
            "program; use paddle_tpu.distributed.send_recv(tensor, src, dst) "
            "or shift(tensor, offset) which fuse the send/recv pair into one "
            "collective-permute")
    return Tensor(_unwrap(tensor))


def recv(tensor, src=0, group=None, sync_op=True):
    """recv_v2 parity — see send()."""
    g = group or _default_group
    if _axis_bound(g.axis):
        raise RuntimeError(
            "one-sided recv() cannot be expressed inside a compiled SPMD "
            "program; use paddle_tpu.distributed.send_recv(tensor, src, dst) "
            "or shift(tensor, offset)")
    return _rewrap(tensor, _unwrap(tensor))


def barrier(group=None):
    """operators/collective/barrier_op: a 1-element psum everyone waits on."""
    g = group or _default_group
    if _axis_bound(g.axis):
        lax.psum(jnp.ones(()), g.axis)
        return
    jax.block_until_ready(jnp.zeros(()))


def wait(tensor, group=None, use_calc_stream=True):
    """c_sync_{calc,comm}_stream: XLA programs are ordered; eager arrays are
    awaited explicitly."""
    x = _unwrap(tensor)
    if not isinstance(x, jax.core.Tracer):
        jax.block_until_ready(x)
    return tensor


# -- model (tensor) parallel API --------------------------------------------

def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split (collective.py:566): build a row/column-
    sharded linear or vocab-sharded embedding.

    TPU-native: rather than manually slicing weights per rank and inserting
    allreduce ops (_parallel_linear collective.py:492), we create the full
    layer and annotate its weight with a PartitionSpec over the mp axis —
    GSPMD partitions the matmul and places the reduction on ICI.
    """
    from .. import nn
    from ..parallel.api import shard_parameter
    from jax.sharding import PartitionSpec as P

    if operation == "linear":
        in_f, out_f = size
        layer = nn.Linear(in_f, out_f, weight_attr=weight_attr,
                          bias_attr=bias_attr)
        if axis == 0:  # row parallel: shard in_features
            shard_parameter(layer.weight, P("mp", None))
        else:          # column parallel: shard out_features
            shard_parameter(layer.weight, P(None, "mp"))
            if layer.bias is not None:
                shard_parameter(layer.bias, P("mp"))
        return layer(x) if isinstance(x, Tensor) else layer
    elif operation == "embedding":
        vocab, emb = size
        layer = nn.Embedding(vocab, emb, weight_attr=weight_attr)
        shard_parameter(layer.weight, P("mp", None))  # vocab-sharded
        return layer(x) if isinstance(x, Tensor) else layer
    raise ValueError(f"unsupported split operation {operation!r}")
