"""Parameter-server stack: host sparse tables + RPC + distributed embedding.

Reference parity map:
  table.py    ≙ paddle/fluid/distributed/table/table.h:32 + large_scale_kv.h
  service.py  ≙ distributed/service/server.h:50, operators/distributed/ RPC
  embedding.py≙ parameter_prefetch/parameter_send sparse pull/push
This is the counterpart of the reference's 31.5K-LoC PS story reshaped for
TPU (BASELINE workload 5, Wide&Deep CTR): sparse on hosts, dense on chips.

Quick start (single process):
    client = LocalPsEndpoint()
    emb = DistributedEmbedding(client, table_id=0, dim=16)
Multi-process:
    server = PsServer(port=0).start(); ...  # or fleet.init_server/run_server
    client = PsClient(server.endpoint)
"""
from .table import SparseTable, DenseTable  # noqa: F401
from .service import PsServer, PsClient, LocalPsEndpoint  # noqa: F401
from .embedding import DistributedEmbedding  # noqa: F401
from .sharded import (  # noqa: F401
    ShardedPsClient, Communicator, GeoCommunicator,
)
from .device_cache import DeviceEmbeddingCache  # noqa: F401

__all__ = ["SparseTable", "DenseTable", "PsServer", "PsClient",
           "LocalPsEndpoint", "DistributedEmbedding", "ShardedPsClient",
           "Communicator", "GeoCommunicator", "DeviceEmbeddingCache"]
