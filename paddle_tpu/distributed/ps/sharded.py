"""Multi-server sharded PS client + communicator modes.

Reference parity:
- table sharding across pservers: distribute_transpiler.py:256 splits
  params into blocks round-robin across endpoints; here sparse rows route
  by ``id % n_servers`` (the same key-block idea without the static block
  table) and each dense table lives on ``table_id % n_servers``.
- communicator modes: operators/distributed/communicator.h —
  AsyncCommunicator (:195, queued sends drained by a thread),
  HalfAsyncCommunicator (:268, batch-merge k steps before sending),
  GeoCommunicator (:340, train on a local copy, ship per-row deltas every
  k steps).

All of it is host-side (DCN): the chip only ever sees the dense jitted
step; pulls/pushes overlap it from threads.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List

import numpy as np

from .service import PsClient


class ShardedPsClient:
    """Fan-out client over N PsServers with id-hash routing; same interface
    as PsClient so trainers are shard-agnostic."""

    def __init__(self, endpoints: List[str], compress: str = "none"):
        if not endpoints:
            raise ValueError("ShardedPsClient needs at least one endpoint")
        self._clients = [PsClient(ep, compress=compress) for ep in endpoints]
        self.n = len(self._clients)
        self._dims: Dict[int, int] = {}

    @staticmethod
    def _run_sharded(fns):
        """Run one thunk per shard in parallel; re-raise the FIRST shard
        failure with its server index (a dead thread must not surface as an
        unrelated KeyError downstream)."""
        errs = []

        def wrap(s, fn):
            try:
                fn()
            except Exception as e:
                errs.append((s, e))

        threads = [threading.Thread(target=wrap, args=(s, fn))
                   for s, fn in enumerate(fns)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            s, e = errs[0]
            raise RuntimeError(f"PS shard {s} failed: {e}") from e

    # -- routing --------------------------------------------------------------
    def _route(self, ids):
        ids = np.asarray(ids)
        shard = (ids % self.n).astype(np.int64)
        return ids, shard

    def create_table(self, table_id: int, kind: str = "sparse", **config):
        if "dim" in config:
            self._dims[table_id] = int(config["dim"])
        if kind == "sparse":
            for c in self._clients:
                c.create_table(table_id, kind, **config)
        else:
            self._clients[table_id % self.n].create_table(table_id, kind,
                                                          **config)

    def pull_sparse(self, table_id: int, ids) -> np.ndarray:
        ids, shard = self._route(ids)
        flat = ids.reshape(-1)
        fshard = shard.reshape(-1)
        if flat.size == 0:
            dim = self._dims.get(table_id, 0)
            return np.zeros(ids.shape + (dim,), np.float32)
        results: Dict[int, np.ndarray] = {}
        idxs: Dict[int, np.ndarray] = {}

        def pull_one(s):
            def go():
                sel = np.nonzero(fshard == s)[0]
                idxs[s] = sel
                if sel.size:
                    results[s] = self._clients[s].pull_sparse(
                        table_id, flat[sel] // self.n)
            return go

        self._run_sharded([pull_one(s) for s in range(self.n)])
        out = None
        for s, sel in idxs.items():
            if not sel.size:
                continue
            vals = results[s]
            if out is None:
                out = np.empty((flat.size,) + vals.shape[1:], vals.dtype)
            out[sel] = vals
        return out.reshape(ids.shape + out.shape[1:])

    def push_sparse(self, table_id: int, ids, grads):
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            return
        grads = np.asarray(grads)
        grads = grads.reshape(ids.size, -1) if grads.size != ids.size \
            else grads.reshape(ids.size)
        shard = (ids % self.n).astype(np.int64)

        def push_one(s):
            def go():
                sel = np.nonzero(shard == s)[0]
                if sel.size:
                    self._clients[s].push_sparse(
                        table_id, ids[sel] // self.n, grads[sel])
            return go

        self._run_sharded([push_one(s) for s in range(self.n)])

    def export_rows(self, table_id: int, ids):
        """Shard-routed pull-with-state (accelerator row-cache fill)."""
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            return (np.zeros((0, self._dims.get(table_id, 0)), np.float32),
                    {})
        shard = (ids % self.n).astype(np.int64)
        rows_parts: Dict[int, tuple] = {}

        def export_one(s):
            def go():
                sel = np.nonzero(shard == s)[0]
                if sel.size:
                    rows_parts[s] = (sel, self._clients[s].export_rows(
                        table_id, ids[sel] // self.n))
            return go

        self._run_sharded([export_one(s) for s in range(self.n)])
        rows = None
        state: Dict[str, np.ndarray] = {}
        for s, (sel, (r, st)) in rows_parts.items():
            if rows is None:
                # size from the first returned part (a fresh client attached
                # to running pservers has no _dims entry)
                rows = np.empty((ids.size,) + r.shape[1:], np.float32)
            rows[sel] = r
            for k, v in st.items():
                if k not in state:
                    state[k] = np.empty((ids.size,) + v.shape[1:],
                                        np.float32)
                state[k][sel] = v
        return rows, state

    def import_rows(self, table_id: int, ids, rows, state=None):
        """Shard-routed raw writeback (cache eviction)."""
        ids = np.asarray(ids).reshape(-1)
        rows = np.asarray(rows, np.float32)
        shard = (ids % self.n).astype(np.int64)
        state = state or {}

        def import_one(s):
            def go():
                sel = np.nonzero(shard == s)[0]
                if sel.size:
                    self._clients[s].import_rows(
                        table_id, ids[sel] // self.n, rows[sel],
                        {k: np.asarray(v)[sel] for k, v in state.items()})
            return go

        self._run_sharded([import_one(s) for s in range(self.n)])

    def pull_dense(self, table_id: int) -> np.ndarray:
        return self._clients[table_id % self.n].pull_dense(table_id)

    def push_dense(self, table_id: int, grads):
        self._clients[table_id % self.n].push_dense(table_id, grads)

    def table_size(self, table_id: int) -> int:
        return sum(c.table_size(table_id) for c in self._clients)

    # -- liveness/barrier fan-out --------------------------------------------
    def start_heartbeat(self, worker_id: int, interval: float = 1.0):
        for c in self._clients:
            c.start_heartbeat(worker_id, interval)

    def stop_heartbeat(self):
        for c in self._clients:
            c.stop_heartbeat()

    def barrier(self, worker_id: int, expected: int, name: str = None,
                timeout: float = 60.0):
        # server 0 coordinates (BarrierTable lives on one pserver)
        return self._clients[0].barrier(worker_id, expected, name, timeout)

    def stop_server(self):
        for c in self._clients:
            try:
                c.stop_server()
            except Exception:
                pass

    def close(self):
        for c in self._clients:
            c.close()


class Communicator:
    """Push-side communicator (communicator.h): decouples trainer steps
    from RPC. Modes:

    sync       — push inline, blocking (SyncCommunicator)
    async      — queue, drained one push at a time (AsyncCommunicator :195)
    half_async — queue, drained with id-merge across up to
                 ``max_merge_var_num`` queued steps so hot rows send one
                 summed gradient (HalfAsyncCommunicator :268)
    geo        — not push-grads at all: every ``k_steps`` ship row DELTAS
                 of a locally-trained copy (GeoCommunicator :340), applied
                 server-side as plain additive updates
    """

    def __init__(self, client, mode="async", max_merge_var_num=4,
                 send_queue_size=16):
        if mode not in ("sync", "async", "half_async"):
            raise ValueError(
                f"Communicator mode {mode!r}: expected sync/async/"
                "half_async (geo mode is GeoCommunicator — it ships row "
                "deltas, not gradients)")
        self.client = client
        self.mode = mode
        self.max_merge = int(max_merge_var_num)
        self._q = queue.Queue(maxsize=int(send_queue_size))
        self._err = None
        self._thread = None
        if mode in ("async", "half_async"):
            self._thread = threading.Thread(target=self._drain, daemon=True)
            self._thread.start()

    def push_sparse(self, table_id, ids, grads):
        if self._err is not None:
            raise self._err
        if self.mode == "sync":
            self.client.push_sparse(table_id, ids, grads)
            return
        self._q.put((table_id, np.asarray(ids), np.asarray(grads)))

    def flush(self):
        if self._thread is not None:
            self._q.join()
        if self._err is not None:
            raise self._err

    def _drain(self):
        while True:
            batch = [self._q.get()]
            if self.mode == "half_async":
                # merge more queued pushes for the same table (batch-merge)
                while len(batch) < self.max_merge:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    batch.append(nxt)
            try:
                by_table: Dict[int, list] = {}
                for tid, ids, grads in batch:
                    n = ids.size
                    ids = ids.reshape(-1)
                    grads = (grads.reshape(n, -1) if grads.size != n
                             else grads.reshape(n))
                    by_table.setdefault(tid, []).append((ids, grads))
                for tid, items in by_table.items():
                    ids = np.concatenate([i for i, _ in items])
                    grads = np.concatenate([g for _, g in items])
                    if self.mode == "half_async":
                        # sum duplicate ids so the server applies one update
                        uniq, inv = np.unique(ids, return_inverse=True)
                        merged = np.zeros((uniq.size,) + grads.shape[1:],
                                          grads.dtype)
                        np.add.at(merged, inv, grads)
                        ids, grads = uniq, merged
                    self.client.push_sparse(tid, ids, grads)
            except Exception as e:       # surface on next push/flush
                self._err = e
            finally:
                for _ in batch:
                    self._q.task_done()


class GeoCommunicator:
    """GeoCommunicator (:340): the worker trains a LOCAL row cache; every
    ``k_steps`` the per-row delta (local - base) ships to the server and
    fresh rows are pulled back. Converges like async SGD with much less
    RPC; the reference's SparseGeoTable applies deltas additively, which
    is exactly push with a raw-delta optimizer ("sum")."""

    def __init__(self, client, table_id, dim, k_steps=4):
        self.client = client
        self.table_id = table_id
        self.k = int(k_steps)
        self.dim = dim
        self._local: Dict[int, np.ndarray] = {}
        self._base: Dict[int, np.ndarray] = {}
        self._step = 0

    def pull(self, ids):
        ids = np.asarray(ids).reshape(-1)
        missing = [i for i in ids.tolist() if i not in self._local]
        if missing:
            rows = self.client.pull_sparse(self.table_id,
                                           np.asarray(missing))
            for i, r in zip(missing, rows):
                self._local[i] = np.array(r, np.float32)
                self._base[i] = np.array(r, np.float32)
        return np.stack([self._local[i] for i in ids.tolist()])

    def apply_local(self, ids, grads, lr=0.05):
        """Local SGD on the cached rows (DeltaSGD of geo mode)."""
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads).reshape(ids.size, -1)
        for i, g in zip(ids.tolist(), grads):
            self._local[i] = self._local[i] - lr * g
        self._step += 1
        if self._step % self.k == 0:
            self._ship_deltas()

    def _ship_deltas(self):
        ids, deltas = [], []
        for i, v in self._local.items():
            d = v - self._base[i]
            if np.any(d):
                ids.append(i)
                deltas.append(-d)      # push() applies -lr*grad; raw "sum"
        if not ids:
            return
        # server table must use optimizer="sum" (raw additive) for geo
        self.client.push_sparse(self.table_id, np.asarray(ids),
                                np.stack(deltas))
        fresh = self.client.pull_sparse(self.table_id, np.asarray(ids))
        for i, r in zip(ids, fresh):
            self._local[i] = np.array(r, np.float32)
            self._base[i] = np.array(r, np.float32)
