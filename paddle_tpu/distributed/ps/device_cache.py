"""Accelerator-resident hot-row cache over a host PS table.

Reference parity: the HeterPS / PSGPU pipeline —
paddle/fluid/framework/fleet/ps_gpu_wrapper.h (BuildGPUPS keeps the pass's
hot sparse rows in GPU HBM), framework/trainer.h:281 PSGPUTrainer, and
framework/device_worker.h HeterBoxWorker: dense + hot sparse on the
accelerator, the full table on host/pserver, writeback at pass end.

TPU-first redesign: instead of a per-pass build, this is a steady-state
software cache.  Rows AND their optimizer state live in device HBM arenas
([capacity+1, dim]; the last slot is a scratch row that absorbs padding
writes).  Per step the host resolves batch ids to slots (LRU, numpy-
vectorized), ships ONLY the miss block, and the train step — one jitted
XLA program — scatters misses in, gathers, computes, and applies the
sparse optimizer rule on-chip.  Steady state with a hot working set moves
zero row bytes over the wire; evictions gather the displaced rows once and
write them back to the host table raw (import_rows), exactly PSGPU's
EndPass writeback.

Slot bookkeeping is factored into :class:`SlotDirectory` so several tables
over the SAME id space (Wide&Deep's wide + deep tables) resolve ids→slots
once and share one LRU — each table then only moves its own rows.

The on-device rules mirror SparseTable._apply_rule (table.py) — sgd /
adagrad / ftrl share state layout with the host table, so rows migrate
between cache and table mid-training without losing accumulator state.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .table import _STATE_SPEC

# rules the cache can run on-chip; state names match _STATE_SPEC
DEVICE_RULES = ("sgd", "adagrad", "ftrl")


def _pad_to_bucket(n: int, bucket: int = 1024) -> int:
    """Round up to a bucket multiple: stable XLA shapes across steps with
    ≤bucket wasted rows (vs power-of-two padding's up-to-2× inflation)."""
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


def pad_adaptive(n: int) -> int:
    """Eighth-octave padding for shapes that feed LARGE jitted programs:
    grain = 2^(⌈log2 n⌉-3) ≤ n/4, so at most 8 distinct compiled shapes
    per doubling of n and ≤25% padding waste — the compromise between
    power-of-two (1 shape/octave, up to 2× waste) and fine buckets (tiny
    waste, recompile storm when n drifts)."""
    if n <= 8:
        return 8
    grain = 1 << max(3, n.bit_length() - 3)
    return ((n + grain - 1) // grain) * grain


def apply_rule_device(opt: str, rows, state, grads, *, lr, eps=1e-8,
                      l1=0.0, l2=0.0, lr_power=-0.5):
    """Vectorized on-chip sparse-optimizer update: ([U,D] rows, state dict,
    [U,D] grads) → (new_rows, new_state).  Traced inside the train step."""
    g = grads.astype(jnp.float32)
    p = rows.astype(jnp.float32)
    if opt == "sgd":
        return p - lr * g, state
    if opt == "adagrad":
        acc = state["acc"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + eps), {"acc": acc}
    if opt == "ftrl":
        sq = state["sq"]
        new_acc = sq + jnp.square(g)
        sigma = (new_acc ** -lr_power - sq ** -lr_power) / lr
        lin = state["lin"] + g - sigma * p
        x = jnp.sign(lin) * l1 - lin
        y = 2.0 * l2 + new_acc ** -lr_power / lr
        new_p = jnp.where(jnp.abs(lin) > l1, x / y, 0.0)
        return new_p, {"sq": new_acc, "lin": lin}
    raise ValueError(f"device cache cannot run rule {opt!r}; "
                     f"supported: {DEVICE_RULES}")


class Resolution(NamedTuple):
    """One step's id→slot resolution (shared across co-located tables)."""
    uniq: np.ndarray          # [U] int64 ids
    slots: np.ndarray         # [U] int64 cache slots
    miss_idx: np.ndarray      # indices into uniq that were misses
    victim_slots: np.ndarray  # slots being reused this step ([0] if none)
    victim_ids: np.ndarray    # the ids formerly in those slots (≥0 only)


class SlotDirectory:
    """Host-side LRU id→slot map for a device cache of ``capacity`` rows.

    Tables over the same id space share ONE directory (resolve once per
    step); each table moves its own rows for the resolved miss/victim sets.
    """

    def __init__(self, capacity: int):
        self.cap = int(capacity)
        self._slot_of: Dict[int, int] = {}
        self._slot_id = np.full(self.cap, -1, np.int64)
        self._last_use = np.zeros(self.cap, np.int64)
        self._n_used = 0
        self._tick = 0
        self._rng_evict = np.random.RandomState(0)   # sampled-LRU candidates
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def resolve(self, uniq: np.ndarray) -> Resolution:
        """Assign every unique id a slot; evict the coldest non-batch slots
        when full.  Call ONCE per step, before any cache fill.

        If the step later fails before the miss rows reach the device
        arena, call :meth:`rollback` with the returned Resolution — the
        miss ids re-miss on retry instead of hitting never-filled slots.
        """
        self._tick += 1
        uniq = np.asarray(uniq, np.int64).ravel()
        get = self._slot_of.get
        slots = np.fromiter((get(i, -1) for i in uniq.tolist()),
                            np.int64, len(uniq))
        miss_i = np.nonzero(slots < 0)[0]
        n_miss = len(miss_i)
        self.hits += len(uniq) - n_miss
        self.misses += n_miss
        # stamp hits NOW: anything at the current tick is batch-protected,
        # which lets eviction test protection in O(1) per candidate
        self._last_use[slots[slots >= 0]] = self._tick
        victims = np.empty(0, np.int64)
        victim_ids = np.empty(0, np.int64)
        if n_miss:
            new_slots, victims, victim_ids = self._allocate(n_miss)
            for i, s in zip(miss_i.tolist(), new_slots.tolist()):
                self._slot_of[int(uniq[i])] = s
                self._slot_id[s] = uniq[i]
            slots[miss_i] = new_slots
        return Resolution(uniq, slots, miss_i, victims, victim_ids)

    def rollback(self, res: Resolution):
        """Undo a resolution whose miss rows never reached the arenas.

        MUST be called before any arena scatter for this resolution (the
        trainer fills every table, then scatters, so a fill failure leaves
        all arenas untouched).  Miss ids are forgotten (they re-miss and
        re-pull on retry) and the evicted victims are RE-INSTATED: their
        arena rows are still intact, so tables whose writeback had not run
        yet lose nothing — and for tables already written back, the cache
        copy is identical to the host copy, consistent either way."""
        for i in res.miss_idx.tolist():
            rid = int(res.uniq[i])
            s = self._slot_of.pop(rid, None)
            if s is not None:
                self._slot_id[s] = -1
                self._last_use[s] = 0
        for s, rid in zip(res.victim_slots.tolist(),
                          res.victim_ids.tolist()):
            self._slot_of[int(rid)] = s
            self._slot_id[s] = rid
            self._last_use[s] = self._tick - 1   # unprotected, still warm
        # reclaim fresh slots handed to the rolled-back misses: fresh
        # allocations are the arena tail, so retries reuse them instead of
        # burning new slots on every failed attempt
        while self._n_used > 0 and self._slot_id[self._n_used - 1] < 0:
            self._n_used -= 1

    def _allocate(self, k: int):
        free = self.cap - self._n_used
        take = min(k, free)
        out = np.empty(k, np.int64)
        victims = np.empty(0, np.int64)
        victim_ids = np.empty(0, np.int64)
        if take:
            # fresh slots are handed out sequentially: the never-used region
            # is exactly [_n_used, cap)
            out[:take] = np.arange(self._n_used, self._n_used + take)
            self._n_used += take
            # stamp immediately: protected from this call's own eviction
            self._last_use[out[:take]] = self._tick
        if take < k:
            reused = self._pick_victims(k - take)
            ids_of = self._slot_id[reused].copy()
            out[take:] = reused
            self._last_use[reused] = self._tick
            # writeback pair: only slots that still hold a live id (a slot
            # rolled back or evicted earlier keeps id -1, no writeback)
            ok = ids_of >= 0
            victims, victim_ids = reused[ok], ids_of[ok]
            for rid in victim_ids.tolist():
                del self._slot_of[int(rid)]
            self._slot_id[reused] = -1
            self.evictions += int(ok.sum())
        return out, victims, victim_ids

    def _pick_victims(self, k: int) -> np.ndarray:
        """k distinct unprotected slots (``_last_use < tick``), coldest
        first.  Sampled eviction: steady-state misses must not pay an
        O(capacity) scan per step (the full arena is 2^20 slots; a batch
        evicts dozens), so try a bounded random sample first — the
        sampled-LRU policy of production caches — and fall back to the
        exact full scan only when the sample can't cover k."""
        tick = self._tick
        sample_n = max(4 * k, 4096)
        if sample_n < self.cap:
            cand = np.unique(self._rng_evict.randint(0, self.cap, sample_n))
            cand = cand[self._last_use[cand] < tick]
            if len(cand) >= k:
                order = np.argpartition(self._last_use[cand], k - 1)[:k]
                return cand[order].astype(np.int64)
        cand = np.nonzero(self._last_use < tick)[0]
        if len(cand) < k:
            raise RuntimeError(
                f"device-cache capacity {self.cap} cannot hold one batch's "
                f"unique ids ({self.cap - len(cand) + k} needed); raise "
                f"capacity above the per-batch unique-id count")
        order = np.argpartition(self._last_use[cand], k - 1)[:k]
        return cand[order].astype(np.int64)

    def items(self):
        """(ids [n], slots [n]) of everything currently cached."""
        n = len(self._slot_of)
        ids = np.fromiter(self._slot_of.keys(), np.int64, n)
        slots = np.fromiter(self._slot_of.values(), np.int64, n)
        return ids, slots

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class DeviceEmbeddingCache:
    """Per-table device arenas + host-table data movement over a (possibly
    shared) SlotDirectory.  The device arrays are OWNED BY THE CALLER's
    train step (pass them in, get updated ones back, donate for in-place
    HBM reuse); this class fills misses and writes evictions back."""

    def __init__(self, client, table_id: int, dim: int,
                 capacity: int = 1 << 20, optimizer: str = "adagrad",
                 lr: float = 0.05, eps: float = 1e-8, l1: float = 0.0,
                 l2: float = 0.0, lr_power: float = -0.5,
                 miss_bucket: int = 1024,
                 directory: Optional[SlotDirectory] = None):
        if optimizer not in DEVICE_RULES:
            raise ValueError(
                f"device cache rule {optimizer!r} not in {DEVICE_RULES}")
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(dim)
        self.directory = directory if directory is not None \
            else SlotDirectory(capacity)
        self.cap = self.directory.cap
        self.opt = optimizer
        self.hyper = dict(lr=lr, eps=eps, l1=l1, l2=l2, lr_power=lr_power)
        self.miss_bucket = int(miss_bucket)
        self._state_names = _STATE_SPEC[optimizer]
        # idempotent: no-op when the embedding layer already created it
        client.create_table(self.table_id, "sparse", dim=dim,
                            optimizer=optimizer, lr=lr, eps=eps, l1=l1,
                            l2=l2, lr_power=lr_power)

    # -- device arenas -------------------------------------------------------
    def init_arenas(self):
        """Fresh device arenas: [cap+1, dim] rows + per-rule state (+1 is
        the scratch slot that absorbs padded scatter/gather traffic)."""
        rows = jnp.zeros((self.cap + 1, self.dim), jnp.float32)
        state = {k: jnp.zeros((self.cap + 1, self.dim), jnp.float32)
                 for k in self._state_names}
        return {"rows": rows, "state": state}

    # -- per-step data movement ---------------------------------------------
    def fill(self, res: Resolution, arenas):
        """Move this table's rows for an already-resolved step: write the
        victim rows back to the host table, pull the miss block.

        Returns (miss_slots [M_pad] int32, miss_rows [M_pad, D] f32,
        miss_state dict) or (None, None, None) when the step had no misses.
        Padded entries of miss_slots point at the scratch slot (index cap).
        """
        if len(res.victim_slots):
            self._writeback(res.victim_slots, res.victim_ids, arenas)
        n_miss = len(res.miss_idx)
        if not n_miss:
            return None, None, None
        rows, state = self.client.export_rows(self.table_id,
                                              res.uniq[res.miss_idx])
        m_pad = _pad_to_bucket(n_miss, self.miss_bucket)
        miss_rows = np.zeros((m_pad, self.dim), np.float32)
        miss_rows[:n_miss] = rows
        miss_state = {}
        for k in self._state_names:
            buf = np.zeros((m_pad, self.dim), np.float32)
            buf[:n_miss] = state[k]
            miss_state[k] = buf
        miss_slots = np.full(m_pad, self.cap, np.int64)     # scratch
        miss_slots[:n_miss] = res.slots[res.miss_idx]
        return miss_slots.astype(np.int32), miss_rows, miss_state

    def prepare(self, uniq: np.ndarray, arenas=None):
        """Single-table convenience: resolve + fill in one call.
        Returns (slots [U] int32, miss_slots, miss_rows, miss_state).
        On any failure the resolution is rolled back, so the directory
        never maps ids to never-filled slots."""
        res = self.directory.resolve(uniq)
        try:
            if len(res.victim_slots) and arenas is None:
                raise RuntimeError(
                    "cache full: prepare() needs the current device arenas "
                    "to write evicted rows back")
            miss_slots, miss_rows, miss_state = self.fill(res, arenas)
        except Exception:
            self.directory.rollback(res)
            raise
        return res.slots.astype(np.int32), miss_slots, miss_rows, miss_state

    def _writeback(self, victim_slots, victim_ids, arenas):
        if not len(victim_ids):
            return
        # one device gather + D2H for rows and state, then raw writeback
        vic = jnp.asarray(victim_slots)
        rows_back = np.asarray(arenas["rows"][vic])
        state_back = {k: np.asarray(arenas["state"][k][vic])
                      for k in self._state_names}
        self.client.import_rows(self.table_id, victim_ids, rows_back,
                                state_back)

    def read_rows(self, uniq: np.ndarray, arenas) -> np.ndarray:
        """Non-mutating read of CURRENT values: cached ids gather from the
        device arena, cold ids pull from the host table.  No LRU update,
        no slot allocation — the eval/serving read path while a trainer
        owns the cache."""
        uniq = np.asarray(uniq, np.int64).ravel()
        get = self.directory._slot_of.get
        slots = np.fromiter((get(i, -1) for i in uniq.tolist()),
                            np.int64, len(uniq))
        out = np.empty((len(uniq), self.dim), np.float32)
        hit = slots >= 0
        if hit.any():
            out[hit] = np.asarray(arenas["rows"][jnp.asarray(slots[hit])])
        cold = ~hit
        if cold.any():
            out[cold] = self.client.pull_sparse(self.table_id, uniq[cold])
        return out

    # -- barriers ------------------------------------------------------------
    def writeback_all(self, arenas):
        """Flush every cached row (+state) to the host table — PSGPU's
        EndPass.  Call before eval/save/shutdown."""
        ids, slots = self.directory.items()
        if not len(ids):
            return
        sl = jnp.asarray(slots)
        rows = np.asarray(arenas["rows"][sl])
        state = {k: np.asarray(arenas["state"][k][sl])
                 for k in self._state_names}
        self.client.import_rows(self.table_id, ids, rows, state)

    # directory passthroughs (back-compat for stats consumers)
    @property
    def hit_rate(self):
        return self.directory.hit_rate

    @property
    def hits(self):
        return self.directory.hits

    @property
    def misses(self):
        return self.directory.misses

    @property
    def evictions(self):
        return self.directory.evictions
