"""Row-block wire codecs for the PS RPC path.

Reference parity: the reference ships gradient/value compression knobs on
its sends (DistributedStrategy fp16 allreduce + the PSLib accessor's
compress options); here the worker↔pserver hop (DCN) carries row blocks
as bf16 (2 bytes/elem) or int8 + per-row scale (~1 byte/elem) instead of
f32.  Encoding is pure numpy bit-twiddling — no ml_dtypes dependency on
the wire, so any peer can decode.

bf16: round-to-nearest-even truncation of the f32 high half; exact for the
first 8 mantissa bits — the same precision the chip computes matmuls in,
so pulls lose nothing the MXU would have kept.
int8: symmetric per-row max-abs quantization with an f32 scale column.
"""
from __future__ import annotations

import numpy as np

MODES = ("none", "bf16", "int8")


def encode_rows(arr: np.ndarray, mode: str):
    """np.float32 [n, d] → wire object (dict for compressed modes)."""
    if mode == "none":
        return np.asarray(arr, np.float32)
    arr = np.ascontiguousarray(arr, np.float32)
    if mode == "bf16":
        u = arr.view(np.uint32).astype(np.uint64)
        # round-to-nearest-even on the dropped half (XLA's f32→bf16 rule);
        # uint64 intermediate so the carry can't wrap a negative value's
        # sign bit away (0xFFFFxxxx + 0x8000 overflows uint32 → +0.0)
        rounded = u + 0x7FFF + ((u >> 16) & 1)
        # exp=0xFF (Inf/NaN) must pass through unrounded: the carry would
        # turn Inf into NaN space, and truncation could strip a low-bits
        # NaN payload down to Inf — force the quiet bit on NaNs instead
        exp_ones = (u & 0x7F800000) == 0x7F800000
        is_nan = exp_ones & ((u & 0x007FFFFF) != 0)
        passthru = u | np.where(is_nan, np.uint64(0x00400000),
                                np.uint64(0))
        rounded = np.where(exp_ones, passthru, rounded)
        return {"codec": "bf16", "shape": arr.shape,
                "data": (rounded >> 16).astype(np.uint16)}
    if mode == "int8":
        flat = arr.reshape(len(arr), -1) if arr.ndim > 1 else arr[:, None]
        scale = np.abs(flat).max(axis=1, keepdims=True) / 127.0
        safe = np.where(scale == 0, 1.0, scale)
        q = np.clip(np.rint(flat / safe), -127, 127).astype(np.int8)
        return {"codec": "int8", "shape": arr.shape,
                "data": q, "scale": scale.astype(np.float32)}
    raise ValueError(f"unknown row codec {mode!r}")


def decode_rows(obj) -> np.ndarray:
    """Inverse of encode_rows; passes plain arrays through."""
    if not isinstance(obj, dict):
        return np.asarray(obj, np.float32)
    codec = obj["codec"]
    if codec == "bf16":
        u = obj["data"].astype(np.uint32) << 16
        return u.view(np.float32).reshape(obj["shape"])
    if codec == "int8":
        return (obj["data"].astype(np.float32) *
                obj["scale"]).reshape(obj["shape"])
    raise ValueError(f"unknown row codec {codec!r}")
