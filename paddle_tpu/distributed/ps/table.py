"""Host-resident parameter-server tables.

Reference parity: the PS table stack —
paddle/fluid/distributed/table/table.h:32 (Table with pull/push sparse+dense
and an Accessor), operators/distributed/large_scale_kv.h (SSD-able sparse
embedding storage with lazy row init), and the per-row optimizers the
accessors apply on push: sgd/adagrad/adam plus the CTR family —
ftrl (operators/optimizers/ftrl_op.h SparseFTRLFunctor), proximal_gd
(proximal_gd_op.h:47), proximal_adagrad (proximal_adagrad_op.h:50),
decayed_adagrad (decayed_adagrad_op.h:63), dpsgd (dpsgd_op.h:68, the
CCS16 DP-SGD rule).

TPU-first: the dense compute (gather, MLP, loss, dense grads) runs on chip;
these tables keep the 100B-parameter-scale sparse embeddings in HOST memory
(the SURVEY §7 phase-8 / HeterPS pattern: "dense on TPU, sparse tables on
hosts").  Storage is a flat numpy ARENA ([capacity, dim] plus parallel
per-slot optimizer-state arrays) with an id→slot dict, so pull is one fancy
gather and push is one vectorized rule application over the touched block —
the vectorized-accessor layout the reference gets from its per-shard Eigen
kernels, instead of a per-row python loop.  Rows are created lazily on
first pull (large_scale_kv.h's init-on-miss).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

# slot-state layout per optimizer rule: name -> per-slot array of row shape
_STATE_SPEC = {
    "sum": (),
    "sgd": (),
    "adagrad": ("acc",),
    "adam": ("m", "v"),
    "ftrl": ("sq", "lin"),
    "proximal_gd": (),
    "proximal_adagrad": ("moment",),
    "decayed_adagrad": ("moment",),
    "dpsgd": (),
}


class SparseTable:
    """id → embedding-row store with a server-side per-row optimizer.

    ≙ CommonSparseTable (distributed/table/common_sparse_table.h) +
    large_scale_kv.h ValueBlock: hash index, lazy init, vectorized rule on
    push.
    """

    def __init__(self, dim: int, optimizer: str = "sgd", lr: float = 0.01,
                 initializer: str = "uniform", init_scale: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: Optional[float] = None,
                 l1: float = 0.0, l2: float = 0.0, lr_power: float = -0.5,
                 decay: float = 0.95, clip: float = 10.0, sigma: float = 1.0,
                 batch_size: float = 16.0, seed: int = 0):
        if optimizer not in _STATE_SPEC:
            raise ValueError(f"unknown sparse optimizer {optimizer}")
        self.dim = int(dim)
        self.opt = optimizer
        self.lr = float(lr)
        if eps is None:
            # per-rule defaults matching the dense optimizer classes
            # (DecayedAdagrad epsilon=1e-6; the adam/adagrad family 1e-8)
            eps = 1e-6 if optimizer == "decayed_adagrad" else 1e-8
        self.beta1, self.beta2, self.eps = beta1, beta2, float(eps)
        self.l1, self.l2, self.lr_power = float(l1), float(l2), float(lr_power)
        self.decay = float(decay)
        self.clip, self.sigma, self.batch_size = (float(clip), float(sigma),
                                                  float(batch_size))
        self._index: Dict[int, int] = {}
        self._n = 0
        self._arena = np.empty((0, self.dim), np.float32)
        self._slot_state: Dict[str, np.ndarray] = {
            k: np.empty((0, self.dim), np.float32)
            for k in _STATE_SPEC[optimizer]}
        self._step = 0
        self._rng = np.random.RandomState(seed)
        self._init = initializer
        self._scale = init_scale

    # -- storage ------------------------------------------------------------
    def _grow(self, need: int):
        cap = len(self._arena)
        if self._n + need <= cap:
            return
        new_cap = max(1024, cap * 2, self._n + need)
        grown = np.empty((new_cap, self.dim), np.float32)
        grown[:self._n] = self._arena[:self._n]
        self._arena = grown
        for k, st in self._slot_state.items():
            g = np.zeros((new_cap, self.dim), np.float32)
            g[:self._n] = st[:self._n]
            self._slot_state[k] = g

    def _init_block(self, k: int) -> np.ndarray:
        if self._init == "zeros":
            return np.zeros((k, self.dim), np.float32)
        return self._rng.uniform(-self._scale, self._scale,
                                 (k, self.dim)).astype(np.float32)

    def _slots_of(self, ids: np.ndarray, create: bool) -> np.ndarray:
        """Vectorized-ish id→slot resolution; -1 for absent (create=False)."""
        idx = self._index
        get = idx.get
        slots = np.fromiter((get(i, -1) for i in ids.tolist()),
                            np.int64, len(ids))
        if create:
            miss = np.nonzero(slots < 0)[0]
            if len(miss):
                # dedupe: repeated new ids in one call share ONE slot
                new_ids = np.unique(ids[miss])
                k = len(new_ids)
                self._grow(k)
                base = self._n
                self._arena[base:base + k] = self._init_block(k)
                for st in self._slot_state.values():
                    st[base:base + k] = 0.0
                for j, rid in enumerate(new_ids.tolist()):
                    idx[int(rid)] = base + j
                self._n = base + k
                slots[miss] = np.fromiter(
                    (idx[int(i)] for i in ids[miss].tolist()),
                    np.int64, len(miss))
        return slots

    # -- pull / push ---------------------------------------------------------
    def pull(self, ids: np.ndarray) -> np.ndarray:
        """[n] ids → [n, dim] rows (rows created on first touch)."""
        ids = np.asarray(ids, np.int64).ravel()
        slots = self._slots_of(ids, create=True)
        return self._arena[slots]

    def push(self, ids: np.ndarray, grads: np.ndarray):
        """Apply the server-side rule to the pushed rows.

        Duplicate ids within one push are sum-merged first (the reference
        merges SelectedRows before the accessor runs, table.h:32 Push).
        """
        self._step += 1
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        if len(uniq) != len(ids):
            merged = np.zeros((len(uniq), self.dim), np.float32)
            np.add.at(merged, inv, grads)
            ids, grads = uniq, merged
        # "sum" accepts deltas for unseen ids (SparseGeoTable accumulates);
        # the optimizer rules touch only rows that exist
        slots = self._slots_of(ids, create=(self.opt == "sum"))
        live = slots >= 0
        if not live.all():
            slots, grads = slots[live], grads[live]
        if len(slots) == 0:
            return
        self._apply_rule(slots, grads)

    def _apply_rule(self, s: np.ndarray, g: np.ndarray):
        P, lr, st = self._arena, self.lr, self._slot_state
        opt = self.opt
        if opt == "sum":
            P[s] -= g
        elif opt == "sgd":
            P[s] -= lr * g
        elif opt == "adagrad":
            acc = st["acc"][s] + g * g
            st["acc"][s] = acc
            P[s] -= lr * g / (np.sqrt(acc) + self.eps)
        elif opt == "adam":
            t = self._step
            bc1 = 1 - self.beta1 ** t
            bc2 = 1 - self.beta2 ** t
            m = self.beta1 * st["m"][s] + (1 - self.beta1) * g
            v = self.beta2 * st["v"][s] + (1 - self.beta2) * g * g
            st["m"][s], st["v"][s] = m, v
            P[s] -= lr * (m / bc1) / (np.sqrt(v / bc2) + self.eps)
        elif opt == "ftrl":
            # ftrl_op.h SparseFTRLFunctor, vectorized
            p, sq = P[s], st["sq"][s]
            new_acc = sq + g * g
            if self.lr_power == -0.5:
                sigma = (np.sqrt(new_acc) - np.sqrt(sq)) / lr
                y = 2.0 * self.l2 + np.sqrt(new_acc) / lr
            else:
                sigma = (new_acc ** -self.lr_power -
                         sq ** -self.lr_power) / lr
                y = 2.0 * self.l2 + new_acc ** -self.lr_power / lr
            lin = st["lin"][s] + g - sigma * p
            st["lin"][s] = lin
            x = np.sign(lin) * self.l1 - lin
            P[s] = np.where(np.abs(lin) > self.l1, x / y, 0.0)
            st["sq"][s] = new_acc
        elif opt == "proximal_gd":
            # proximal_gd_op.h:47
            P[s] = self._prox_shrink(P[s] - lr * g, lr)
        elif opt == "proximal_adagrad":
            # proximal_adagrad_op.h:50
            m = st["moment"][s] + g * g
            st["moment"][s] = m
            # eps guard (deviation from proximal_adagrad_op.h:51, which
            # divides by bare sqrt and NaNs on zero-grad/zero-moment elems)
            lr_eff = lr / (np.sqrt(m) + self.eps)
            P[s] = self._prox_shrink(P[s] - lr_eff * g, lr_eff)
        elif opt == "decayed_adagrad":
            # decayed_adagrad_op.h:63
            m = self.decay * st["moment"][s] + (1 - self.decay) * g * g
            st["moment"][s] = m
            P[s] -= lr * g / (np.sqrt(m) + self.eps)
        elif opt == "dpsgd":
            # dpsgd_op.h:68 applied PER ROW (the per-row-accessor contract:
            # a row's update must not depend on which other ids share the
            # push call — ShardedPsClient splits pushes by id%shards):
            # clip each row's l2 norm, one noise sample per row
            norm = np.sqrt(np.sum(g * g, axis=1, keepdims=True))
            scale = np.maximum(norm / self.clip, 1.0)
            noise = self._rng.normal(
                0.0, self.sigma, (len(g), 1)).astype(np.float32)
            P[s] -= lr * (g / scale + noise / self.batch_size)

    def _prox_shrink(self, prox, lr_eff):
        """sign(prox)·max(|prox| − lr·l1, 0)/(1 + lr·l2) — with l1 == 0 this
        reduces exactly to the reference's else-branch prox/(1+lr·l2), so one
        formula serves both (proximal_gd_op.h:47-56)."""
        return (np.sign(prox) *
                np.maximum(np.abs(prox) - lr_eff * self.l1, 0.0) /
                (1.0 + lr_eff * self.l2))

    # -- raw row access (device-cache writeback / checkpoint shards) ---------
    def export_rows(self, ids: np.ndarray):
        """(rows [n,D], state dict of [n,D]) for ids; missing ids get freshly
        initialized rows — the pull-with-state used by accelerator row caches
        (HeterPS pulls value+opt state into the GPU cache, heter_ps/)."""
        ids = np.asarray(ids, np.int64).ravel()
        slots = self._slots_of(ids, create=True)
        return (self._arena[slots],
                {k: v[slots] for k, v in self._slot_state.items()})

    def import_rows(self, ids: np.ndarray, rows: np.ndarray,
                    state: Optional[Dict[str, np.ndarray]] = None):
        """Store raw row values (+ optimizer state) — the cache-eviction
        writeback: values were already optimized elsewhere, no rule applied."""
        ids = np.asarray(ids, np.int64).ravel()
        slots = self._slots_of(ids, create=True)
        self._arena[slots] = np.asarray(rows, np.float32)
        if state:
            for k, v in state.items():
                self._slot_state[k][slots] = np.asarray(v, np.float32)

    # -- introspection / checkpoint ------------------------------------------
    def __len__(self):
        return self._n

    def state_dict(self):
        spec = _STATE_SPEC[self.opt]
        return {"dim": self.dim, "opt": self.opt, "lr": self.lr,
                "step": self._step,
                "rows": {k: self._arena[s].copy()
                         for k, s in self._index.items()},
                "state": {k: tuple(self._slot_state[n][s].copy()
                                   for n in spec)
                          for k, s in self._index.items()} if spec else {}}

    def load_state_dict(self, sd):
        self.dim = sd["dim"]
        self._step = sd["step"]
        n = len(sd["rows"])
        # raw slot assignment: saved values land directly in the arena — no
        # _init_block draws, so the table RNG stays where a never-
        # checkpointed run would have it (restore must not perturb the
        # lazy-init stream)
        names = _STATE_SPEC[self.opt]
        cap = max(n, 1)
        self._arena = np.empty((cap, self.dim), np.float32)
        self._slot_state = {k: np.zeros((cap, self.dim), np.float32)
                            for k in names}
        self._index, self._n = {}, n
        for i, (k, v) in enumerate(sd["rows"].items()):
            self._index[int(k)] = i
            self._arena[i] = np.asarray(v, np.float32)
        for k, tup in sd.get("state", {}).items():
            i = self._index[int(k)]
            for name, arr in zip(names, tup):
                self._slot_state[name][i] = np.asarray(arr, np.float32)


class DenseTable:
    """Flat dense parameter block with SGD-on-push (≙ common_dense_table)."""

    def __init__(self, shape, lr: float = 0.01, seed: int = 0):
        rng = np.random.RandomState(seed)
        self.value = (rng.standard_normal(shape) *
                      0.01).astype(np.float32)
        self.lr = float(lr)

    def pull(self) -> np.ndarray:
        return self.value.copy()

    def push(self, grad: np.ndarray):
        self.value -= self.lr * np.asarray(grad, np.float32)
