"""DistributedEmbedding: host-table embedding with device-side compute.

Reference parity: the sparse-table lookup path — lookup_sparse_table ops +
parameter_prefetch (operators/distributed/parameter_prefetch.cc pulls rows
for the batch's ids from pservers) and parameter_send's sparse push of
SelectedRows grads.

TPU-first (SURVEY §7 phase 8 / HeterPS): per step,
  1. host: unique the batch ids, PULL only those rows from the table,
  2. device: one gather ( + the rest of the dense model) on chip,
  3. backward: the pulled row-block is a leaf Tensor, so the tape leaves a
     dense [U, D] grad on it (U = unique ids in batch — small),
  4. host: PUSH (ids, row grads) — the server applies its per-row rule.
So the chip only ever sees O(batch) rows of the (unbounded) table.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...nn.layer.layers import Layer
from ... import nn


class DistributedEmbedding(Layer):
    """Embedding whose weights live in a PS table (local or remote client)."""

    def __init__(self, client, table_id: int, dim: int,
                 optimizer: str = "adagrad", lr: float = 0.05,
                 init_scale: float = 0.01, **table_kw):
        super().__init__()
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(dim)
        client.create_table(self.table_id, "sparse", dim=dim,
                            optimizer=optimizer, lr=lr,
                            init_scale=init_scale, **table_kw)
        self._pending: List[Tuple[np.ndarray, Tensor]] = []

    def pull_padded_rows(self, uniq):
        """Host pull + power-of-two padding. A stable [U_pad, D] shape
        means the downstream XLA programs are compiled once, not per
        distinct unique-id count (recompile-per-batch would dominate).
        Shared by the eager forward and the fused PS trainers."""
        rows = self.client.pull_sparse(self.table_id, uniq)       # host
        n = len(uniq)
        n_pad = max(8, 1 << (n - 1).bit_length())
        if n_pad != n:
            rows = np.concatenate(
                [rows, np.zeros((n_pad - n, self.dim), np.float32)])
        return rows

    def forward(self, ids):
        from ...nn import functional as F
        ids_arr = ids._value if isinstance(ids, Tensor) else np.asarray(ids)
        ids_np = np.asarray(ids_arr)
        uniq, inv = np.unique(ids_np, return_inverse=True)
        rows = self.pull_padded_rows(uniq)
        w_rows = Tensor(jnp.asarray(rows), stop_gradient=False)   # leaf
        w_rows.name = f"dist_emb_{self.table_id}_rows"
        if self.training:
            self._pending.append((uniq, w_rows))
        inv_t = Tensor(jnp.asarray(inv.reshape(ids_np.shape), jnp.int32))
        return F.embedding(inv_t, w_rows)                          # device

    def flush_grads(self):
        """Push accumulated row grads to the table (the per-step
        parameter_send).  Call after backward, before/at optimizer.step."""
        for uniq, w_rows in self._pending:
            if w_rows.grad is not None:
                grads = np.asarray(w_rows.grad._value)[:len(uniq)]
                self.client.push_sparse(self.table_id, uniq, grads)
        self._pending.clear()

    def table_size(self):
        return self.client.table_size(self.table_id)
