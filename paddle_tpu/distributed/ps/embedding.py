"""DistributedEmbedding: host-table embedding with device-side compute.

Reference parity: the sparse-table lookup path — lookup_sparse_table ops +
parameter_prefetch (operators/distributed/parameter_prefetch.cc pulls rows
for the batch's ids from pservers) and parameter_send's sparse push of
SelectedRows grads.

TPU-first (SURVEY §7 phase 8 / HeterPS): per step,
  1. host: unique the batch ids, PULL only those rows from the table,
  2. device: one gather ( + the rest of the dense model) on chip,
  3. backward: the pulled row-block is a leaf Tensor, so the tape leaves a
     dense [U, D] grad on it (U = unique ids in batch — small),
  4. host: PUSH (ids, row grads) — the server applies its per-row rule.
So the chip only ever sees O(batch) rows of the (unbounded) table.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...nn.layer.layers import Layer
from ... import nn


class DistributedEmbedding(Layer):
    """Embedding whose weights live in a PS table (local or remote client)."""

    def __init__(self, client, table_id: int, dim: int,
                 optimizer: str = "adagrad", lr: float = 0.05,
                 init_scale: float = 0.01, **table_kw):
        super().__init__()
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.table_kw = dict(table_kw)
        client.create_table(self.table_id, "sparse", dim=dim,
                            optimizer=optimizer, lr=lr,
                            init_scale=init_scale, **table_kw)
        self._pending: List[Tuple[np.ndarray, Tensor]] = []

    def pull_padded_rows(self, uniq):
        """Host pull + quarter-octave padding (device_cache.pad_adaptive).
        The [U_pad, D] shape feeds the fused train-step jit, so the grain
        balances recompile count (≤8 shapes per doubling of U) against
        wire-padding waste (≤25%, vs power-of-two's up-to-2×).  Shared by
        the eager forward and the fused PS trainers."""
        from .device_cache import pad_adaptive
        rows = self.client.pull_sparse(self.table_id, uniq)       # host
        n = len(uniq)
        n_pad = pad_adaptive(n)
        if n_pad != n:
            rows = np.concatenate(
                [rows, np.zeros((n_pad - n, self.dim), np.float32)])
        return rows

    def forward(self, ids):
        from ...nn import functional as F
        ids_arr = ids._value if isinstance(ids, Tensor) else np.asarray(ids)
        ids_np = np.asarray(ids_arr)
        uniq, inv = np.unique(ids_np, return_inverse=True)
        reader = getattr(self, "_cache_read", None)
        if reader is not None:
            # a trainer-owned device cache holds the authoritative rows
            # (host table stale until flush).  Eval reads through it; an
            # eager TRAINING forward would fork the parameter state between
            # the cache and the push path, so refuse loudly.
            if self.training:
                raise RuntimeError(
                    "DistributedEmbedding is bound to a trainer's device "
                    "cache; train through the trainer, or call .eval() "
                    "for read-through inference")
            from .device_cache import pad_adaptive
            rows = reader(uniq)
            n, n_pad = len(uniq), pad_adaptive(len(uniq))
            if n_pad != n:
                rows = np.concatenate(
                    [rows, np.zeros((n_pad - n, self.dim), np.float32)])
            w_rows = Tensor(jnp.asarray(rows), stop_gradient=True)
        else:
            rows = self.pull_padded_rows(uniq)
            w_rows = Tensor(jnp.asarray(rows), stop_gradient=False)  # leaf
            w_rows.name = f"dist_emb_{self.table_id}_rows"
            if self.training:
                self._pending.append((uniq, w_rows))
        inv_t = Tensor(jnp.asarray(inv.reshape(ids_np.shape), jnp.int32))
        return F.embedding(inv_t, w_rows)                          # device

    def flush_grads(self):
        """Push accumulated row grads to the table (the per-step
        parameter_send).  Call after backward, before/at optimizer.step."""
        for uniq, w_rows in self._pending:
            if w_rows.grad is not None:
                grads = np.asarray(w_rows.grad._value)[:len(uniq)]
                self.client.push_sparse(self.table_id, uniq, grads)
        self._pending.clear()

    def table_size(self):
        return self.client.table_size(self.table_id)
