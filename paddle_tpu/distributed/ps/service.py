"""Parameter-server RPC service: pull/push over TCP.

Reference parity: the brpc/grpc PS service —
paddle/fluid/distributed/service/server.h:50 (PSServer hosting tables),
operators/distributed/ RPCServer/RPCClient + parameter_send/parameter_recv
(sparse-table pull/push messages), listen_and_serv_op.cc's serving loop.

TPU-first framing: chips never block on this path — workers batch pull/push
of HOST-side sparse tables around the dense on-chip step, so the RPC is a
host-to-host side channel (DCN), exactly the HeterPS split.  Wire format is
length-prefixed pickles over a socket; one thread per connection.  This is
deliberately minimal but REAL: multiple worker processes can share one table
server (tested via subprocess in tests/test_ps.py).
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Dict, Optional

import numpy as np

from .table import SparseTable, DenseTable
from .codec import encode_rows, decode_rows

_LEN = struct.Struct("!Q")


def _send_msg(sock: socket.socket, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    body = _recv_exact(sock, n)
    return None if body is None else pickle.loads(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class PsServer:
    """Hosts tables; serves pull/push/barrier (server.h:50 + listen_and_serv).

    Thread-per-connection; table mutations are serialized by a lock (the
    reference's per-shard mutexes collapse to one — host python, not the
    hot path)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout: float = 10.0):
        self._tables: Dict[int, object] = {}
        self._lock = threading.RLock()  # _handle -> create_table re-enters
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.endpoint = "%s:%d" % self._sock.getsockname()[:2]
        self._running = False
        self._threads = []
        self._barrier_count = 0
        self._barrier_waiters = []
        # worker liveness (heart_beat_monitor.h:51): workers that stop
        # beating past the timeout are evicted — barriers no longer wait
        # for them, so one dead trainer cannot hang the job
        self._hb_timeout = heartbeat_timeout
        self._hb_last: Dict[int, float] = {}
        self._hb_dead: set = set()
        self._barrier_cv = threading.Condition()
        self._barrier_arrived: Dict[str, set] = {}

    def create_table(self, table_id: int, kind: str = "sparse", **kw):
        with self._lock:
            if table_id not in self._tables:
                self._tables[table_id] = (SparseTable(**kw) if kind == "sparse"
                                          else DenseTable(**kw))
        return self._tables[table_id]

    # -- serving loop ---------------------------------------------------------
    def start(self):
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    break
                reply = self._handle(msg)
                _send_msg(conn, reply)
        finally:
            conn.close()

    # -- worker liveness ------------------------------------------------------
    def _alive_workers(self, expected):
        import time
        now = time.monotonic()
        alive = set()
        for w in range(expected):
            if w in self._hb_dead:
                continue
            last = self._hb_last.get(w)
            if last is None or now - last <= self._hb_timeout:
                alive.add(w)
            else:
                self._hb_dead.add(w)       # evict (HeartBeatMonitor::Run)
        return alive

    def _barrier(self, name, worker_id, expected, timeout):
        """Block until every LIVE worker arrives (barrier_table semantics
        with heart_beat_monitor eviction). State is refcounted: when the
        last waiter leaves a completed barrier its entry is dropped, so a
        restarted worker reusing the same name sequence gets a FRESH
        barrier instead of sailing through on stale arrivals."""
        import time
        deadline = time.monotonic() + timeout
        with self._barrier_cv:
            st = self._barrier_arrived.setdefault(
                name, {"arrived": set(), "inside": 0})
            st["arrived"].add(worker_id)
            st["inside"] += 1
            self._barrier_cv.notify_all()
            try:
                while True:
                    alive = self._alive_workers(expected)
                    if alive - st["arrived"] == set():
                        self._barrier_cv.notify_all()
                        return {"ok": True, "alive": sorted(alive)}
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return {"ok": False, "error": "barrier timeout",
                                "waiting_for": sorted(alive - st["arrived"])}
                    self._barrier_cv.wait(min(left, 0.25))
            finally:
                st["inside"] -= 1
                if st["inside"] == 0:
                    self._barrier_arrived.pop(name, None)

    def _handle(self, msg):
        op = msg["op"]
        if op == "heartbeat":
            import time
            wid = int(msg["worker_id"])
            self._hb_last[wid] = time.monotonic()
            # a worker that resumes beating (long GC / compile pause)
            # rejoins — eviction is not a death sentence
            self._hb_dead.discard(wid)
            return {"ok": True}
        if op == "barrier":
            return self._barrier(msg.get("name", ""), int(msg["worker_id"]),
                                 int(msg["expected"]),
                                 float(msg.get("timeout", 60.0)))
        with self._lock:
            if op == "create_table":
                self.create_table(msg["table_id"], msg.get("kind", "sparse"),
                                  **msg.get("config", {}))
                return {"ok": True}
            table = self._tables.get(msg.get("table_id"))
            if op == "pull_sparse":
                # codec'd reply when the client asks (DCN row compression)
                vals = table.pull(msg["ids"])
                return {"ok": True,
                        "values": encode_rows(vals, msg.get("codec", "none"))}
            if op == "push_sparse":
                table.push(msg["ids"], decode_rows(msg["grads"]))
                return {"ok": True}
            if op == "export_rows":
                # ALWAYS full precision: exported rows+state become the
                # cache's master copy (lossy codecs are for gradient pushes
                # and read-only pulls; quantizing an adagrad accumulator to
                # 0 would blow the on-chip update to lr*g*1e8)
                rows, state = table.export_rows(msg["ids"])
                return {"ok": True, "rows": rows, "state": state}
            if op == "import_rows":
                table.import_rows(
                    msg["ids"], decode_rows(msg["rows"]),
                    {k: decode_rows(v)
                     for k, v in (msg.get("state") or {}).items()})
                return {"ok": True}
            if op == "pull_dense":
                return {"ok": True, "values": table.pull()}
            if op == "push_dense":
                table.push(msg["grads"])
                return {"ok": True}
            if op == "table_size":
                return {"ok": True, "size": len(table)}
            if op == "stop":
                # release the bound port immediately (the accept loop wakes
                # on the OSError) so a later init_server on this fixed
                # endpoint doesn't hit EADDRINUSE; the live conn still gets
                # the reply below
                self._running = False
                try:
                    self._sock.close()
                except OSError:
                    pass
                return {"ok": True}
        raise ValueError(f"unknown PS op {op}")

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class PsClient:
    """Worker-side stub (RPCClient + Communicator's synchronous send path —
    the async aggregation threads of communicator.h:195 are unnecessary
    here because pushes batch per train step already)."""

    def __init__(self, endpoint: str, compress: str = "none"):
        from .codec import MODES
        if compress not in MODES:
            raise ValueError(f"compress must be one of {MODES}")
        self._endpoint = endpoint
        self._codec = compress
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=60)
        self._lock = threading.Lock()
        self._hb_thread = None
        self._hb_stop = threading.Event()
        self._barrier_seq = 0

    def _call(self, **msg):
        with self._lock:
            _send_msg(self._sock, msg)
            out = _recv_msg(self._sock)
        if out is None or not out.get("ok"):
            raise RuntimeError(f"PS call failed: {msg.get('op')}: "
                               f"{(out or {}).get('error', 'conn closed')}")
        return out

    def _call_fresh(self, timeout=90.0, **msg):
        """Blocking ops (barrier) and side-channel ops (heartbeat) use their
        own connection so the pull/push socket never stalls behind them."""
        host, port = self._endpoint.rsplit(":", 1)
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as s:
            _send_msg(s, msg)
            out = _recv_msg(s)
        if out is None or not out.get("ok"):
            raise RuntimeError(f"PS call failed: {msg.get('op')}: "
                               f"{(out or {}).get('error', 'conn closed')}")
        return out

    # -- liveness (heart_beat_monitor.h worker side) -------------------------
    def start_heartbeat(self, worker_id: int, interval: float = 1.0):
        if self._hb_thread is not None:
            return
        self._hb_stop = threading.Event()   # restartable after stop_heartbeat

        def beat():
            while not self._hb_stop.wait(interval):
                try:
                    self._call_fresh(op="heartbeat", worker_id=worker_id,
                                     timeout=10.0)
                except Exception:
                    return          # server gone: trainer notices on RPC
        self._call_fresh(op="heartbeat", worker_id=worker_id, timeout=10.0)
        self._hb_thread = threading.Thread(target=beat, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self):
        self._hb_stop.set()
        self._hb_thread = None

    def barrier(self, worker_id: int, expected: int, name: str = None,
                timeout: float = 60.0):
        """Job-wide barrier that only waits for LIVE workers; returns the
        list of workers it synchronized with."""
        self._barrier_seq += 1
        name = name or f"b{self._barrier_seq}"
        out = self._call_fresh(op="barrier", worker_id=worker_id,
                               expected=expected, name=name,
                               timeout=timeout + 5.0)
        return out["alive"]

    def create_table(self, table_id: int, kind: str = "sparse", **config):
        self._call(op="create_table", table_id=table_id, kind=kind,
                   config=config)

    def pull_sparse(self, table_id: int, ids) -> np.ndarray:
        return decode_rows(self._call(op="pull_sparse", table_id=table_id,
                                      ids=np.asarray(ids),
                                      codec=self._codec)["values"])

    def push_sparse(self, table_id: int, ids, grads):
        ids = np.asarray(ids)
        if ids.size == 0:
            return
        self._call(op="push_sparse", table_id=table_id, ids=ids,
                   grads=encode_rows(np.asarray(grads, np.float32)
                                     .reshape(ids.size, -1), self._codec))

    def export_rows(self, table_id: int, ids):
        """(rows, state) pull-with-state for accelerator row caches.
        Always full precision — see the server-side rationale."""
        out = self._call(op="export_rows", table_id=table_id,
                         ids=np.asarray(ids))
        return np.asarray(out["rows"]), {k: np.asarray(v)
                                         for k, v in out["state"].items()}

    def import_rows(self, table_id: int, ids, rows, state=None):
        """Raw writeback of optimized rows (+ state) — cache eviction.
        Always full precision: these are the master values, not deltas."""
        self._call(op="import_rows", table_id=table_id, ids=np.asarray(ids),
                   rows=np.asarray(rows, np.float32),
                   state={k: np.asarray(v, np.float32)
                          for k, v in (state or {}).items()})

    def pull_dense(self, table_id: int) -> np.ndarray:
        return self._call(op="pull_dense", table_id=table_id)["values"]

    def push_dense(self, table_id: int, grads):
        self._call(op="push_dense", table_id=table_id,
                   grads=np.asarray(grads))

    def table_size(self, table_id: int) -> int:
        return self._call(op="table_size", table_id=table_id)["size"]

    def stop_server(self):
        try:
            self._call(op="stop")
        except Exception:
            pass

    def close(self):
        self._sock.close()


class LocalPsEndpoint:
    """In-process 'client' over a table dict — single-trainer fast path (no
    sockets), same interface as PsClient.  ≙ running trainer+pserver in one
    process for tests (test_dist_base local mode)."""

    def __init__(self):
        import threading
        self._tables: Dict[int, object] = {}
        # async-communicator mode pushes from a drain thread while the
        # trainer pulls: serialize table access so a pull can never see a
        # torn (half-applied) row update
        self._lock = threading.RLock()

    def create_table(self, table_id: int, kind: str = "sparse", **config):
        with self._lock:
            if table_id not in self._tables:
                self._tables[table_id] = (SparseTable(**config)
                                          if kind == "sparse"
                                          else DenseTable(**config))

    def pull_sparse(self, table_id, ids):
        with self._lock:
            return self._tables[table_id].pull(np.asarray(ids))

    def push_sparse(self, table_id, ids, grads):
        with self._lock:
            self._tables[table_id].push(np.asarray(ids), np.asarray(grads))

    def export_rows(self, table_id, ids):
        with self._lock:
            return self._tables[table_id].export_rows(np.asarray(ids))

    def import_rows(self, table_id, ids, rows, state=None):
        with self._lock:
            self._tables[table_id].import_rows(np.asarray(ids), rows, state)

    def pull_dense(self, table_id):
        return self._tables[table_id].pull()

    def push_dense(self, table_id, grads):
        self._tables[table_id].push(np.asarray(grads))

    def table_size(self, table_id):
        return len(self._tables[table_id])

    def close(self):
        pass
