"""Fleet facade: the distributed-training front door.

Reference parity: python/paddle/distributed/fleet/base/fleet_base.py —
``Fleet`` singleton (:63) with init (:130), distributed_optimizer (:593),
distributed_model (:638), minimize (:988); the meta-optimizer factory
(:1068-1105) that ranks and composes strategy wrappers.

TPU-native: strategies do not rewrite op programs.  ``distributed_optimizer``
returns a DistributedOptimizer that carries the DistributedStrategy; when a
step is compiled (directly, via hapi, or via fleet.minimize) the strategy
lowers onto the SPMD engine:
  sharding→zero, recompute→remat, gradient_merge→accumulate_steps,
  amp→bf16 compute dtype, tensor_parallel/pipeline→mesh axes.
The whole meta-optimizer ranking machinery collapses into this single
translation, because composition happens inside ONE jitted step rather than
by nested program rewriting.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...parallel import mesh as mesh_mod
from ...parallel.train_step import TrainStep
from ..parallel_env import init_parallel_env, ParallelEnv
from .base.distributed_strategy import DistributedStrategy
from .base.role_maker import PaddleCloudRoleMaker, RoleMakerBase


class DistributedOptimizer:
    """Strategy-carrying optimizer wrapper (the composed meta-optimizer)."""

    def __init__(self, optimizer, strategy: DistributedStrategy):
        self._inner = self._apply_optimizer_swaps(optimizer, strategy)
        self.user_defined_strategy = strategy

    @staticmethod
    def _apply_optimizer_swaps(optimizer, strategy):
        """strategy.lamb/lars swap the inner optimizer (the reference's
        LambOptimizer/LarsOptimizer meta-optimizers replace the user's
        momentum/adam the same way)."""
        from ...optimizer.optimizer import Lamb, LarsMomentum
        if strategy is None:
            return optimizer
        params = getattr(optimizer, "_parameters", None)
        # carry the user's LR schedule object (not a float snapshot) and
        # grad clip through the swap
        lr = getattr(optimizer, "_lr", None)
        clip = getattr(optimizer, "_grad_clip", None)
        if getattr(strategy, "lamb", False) and \
                not isinstance(optimizer, Lamb):
            cfg = strategy.lamb_configs
            return Lamb(learning_rate=lr,
                        lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
                        parameters=params, grad_clip=clip)
        if getattr(strategy, "lars", False) and \
                not isinstance(optimizer, LarsMomentum):
            cfg = strategy.lars_configs
            return LarsMomentum(
                learning_rate=lr,
                momentum=getattr(optimizer, "_momentum", 0.9),
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                parameters=params, grad_clip=clip)
        if getattr(strategy, "dgc", False):
            # the reference's DGCOptimizer REPLACES Momentum with
            # DGCMomentumOptimizer — the momentum moves INSIDE the
            # compressor. Equivalent here: swap Momentum → plain SGD and
            # carry its coefficient into dgc_momentum
            # (train_step_options reads it back); keeping Momentum outside
            # would compound momentum twice.
            from ...optimizer.optimizer import SGD, Momentum
            if isinstance(optimizer, Momentum):
                strategy.dgc_configs = dict(
                    strategy.dgc_configs or {},
                    _momentum=float(optimizer._momentum))
                return SGD(learning_rate=lr, parameters=params,
                           grad_clip=clip)
            if not isinstance(optimizer, SGD):
                raise NotImplementedError(
                    "strategy.dgc requires a Momentum (or SGD) inner "
                    "optimizer — the reference's DGCOptimizer only "
                    "applies to Momentum (dgc_optimizer.py)")
        return optimizer

    # strategy → engine options ---------------------------------------------
    def train_step_options(self):
        from .ledger import check_strategy
        s = self.user_defined_strategy
        check_strategy(s)        # unsupported flags raise, never sit inert
        opts = {}
        if s.recompute:
            opts["remat"] = True
        if s.sharding:
            opts["zero"] = int(s.sharding_configs.get("stage", 1))
        if s.gradient_merge:
            opts["accumulate_steps"] = int(s.gradient_merge_configs["k_steps"])
        if s.pipeline:
            opts.setdefault("accumulate_steps",
                            int(s.pipeline_configs.get("accumulate_steps", 1)))
        if s.amp:
            if s.amp_configs.get("use_pure_bf16", True):
                opts["compute_dtype"] = jnp.bfloat16
            else:
                opts["compute_dtype"] = jnp.float16
        if s.localsgd:
            opts["localsgd_k"] = int(s.localsgd_configs.get("k_steps", 1))
            opts["localsgd_begin"] = int(
                s.localsgd_configs.get("begin_step", 1))
        if s.dgc:
            cfg = s.dgc_configs or {}
            # reference dgc_configs: rampup_begin_step + sparsity list
            # (the engine applies the final sparsity after rampup);
            # _momentum carries the swapped-out Momentum's coefficient
            sp = cfg.get("sparsity", [0.999])
            opts["dgc_sparsity"] = float(sp[-1] if isinstance(
                sp, (list, tuple)) else sp)
            opts["dgc_rampup_begin"] = int(
                cfg.get("rampup_begin_step", 1))
            opts["dgc_momentum"] = float(cfg.get("_momentum", 0.9))
        if s.a_sync:
            raise NotImplementedError(
                "DistributedStrategy.a_sync is the parameter-server async "
                "mode; it configures the ps/ trainer (rec.WideDeepTrainer "
                "async_push), not the collective TrainStep path")
        return opts

    def build_train_step(self, layer, loss_fn=None, **overrides):
        opts = self.train_step_options()
        opts.update(overrides)
        return TrainStep(layer, self._inner, loss_fn, **opts)

    # optimizer protocol passthrough ----------------------------------------
    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program, parameter_list,
                                    no_grad_set)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, state):
        return self._inner.set_state_dict(state)


class Fleet:
    """fleet_base.py:63 parity."""

    def __init__(self):
        self._role_maker: RoleMakerBase = None
        self._user_defined_strategy: DistributedStrategy = None
        self._is_collective = False
        self._runtime_handle = None

    # -- init ----------------------------------------------------------------
    def init(self, role_maker=None, is_collective=False, strategy=None):
        self._is_collective = is_collective
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._user_defined_strategy = strategy or DistributedStrategy()
        if is_collective:
            # mesh axes from strategy degrees
            s = self._user_defined_strategy
            axes = {}
            if s.tensor_parallel:
                axes[mesh_mod.MP_AXIS] = int(
                    s.tensor_parallel_configs["tensor_parallel_degree"])
            if s.pipeline:
                axes[mesh_mod.PP_AXIS] = int(
                    s.pipeline_configs.get("pp_degree", 1))
            if s.sequence_parallel:
                axes[mesh_mod.SP_AXIS] = int(
                    s.sequence_parallel_configs.get("sp_degree", 1))
            axes[mesh_mod.DP_AXIS] = -1
            init_parallel_env(mesh_axes=axes)
        return self

    # -- topology queries ----------------------------------------------------
    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return self._role_maker.is_server()

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    # -- training ------------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._user_defined_strategy = strategy
        return DistributedOptimizer(
            optimizer, self._user_defined_strategy or DistributedStrategy())

    def distributed_model(self, model):
        from ..parallel import DataParallel
        s = self._user_defined_strategy
        if s is not None and getattr(s, "sync_batch_norm", False):
            # the reference's sync_batch_norm pass rewrites program BN ops;
            # the layer-world equivalent is the SyncBatchNorm converter
            # (global batch stats via GSPMD's cross-dp reduction)
            from ...nn import SyncBatchNorm
            model = SyncBatchNorm.convert_sync_batchnorm(model)
        return DataParallel(model)

    def minimize(self, loss=None, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        raise RuntimeError(
            "fleet.minimize on a bare loss requires static mode; in the TPU "
            "build use optimizer.build_train_step(layer, loss_fn) or hapi "
            "Model.prepare(fleet_optimizer) for the compiled SPMD path")

    # -- checkpoint ----------------------------------------------------------
    def save_persistables(self, executor=None, dirname=None, main_program=None,
                          mode=0):
        """fleet_base parity: persist trainable state. ``main_program`` may be
        a Layer (dygraph) or anything with state_dict(); rank 0 writes."""
        import os
        from ...framework.io_state import save
        if not dirname:
            raise ValueError("save_persistables requires dirname")
        if not self.is_first_worker():
            return
        os.makedirs(dirname, exist_ok=True)
        target = main_program if main_program is not None else executor
        if target is None or not hasattr(target, "state_dict"):
            raise NotImplementedError(
                "fleet.save_persistables needs a Layer/Model with "
                "state_dict() (static Program persistables arrive with "
                "paddle_tpu.static)")
        save(target.state_dict(), os.path.join(dirname, "model.pdparams"))

    # -- parameter-server mode (fleet_base.py init_server/run_server/
    #    init_worker; served by the ps/ stack — server.h:50 analogue) --------
    def init_server(self, *args, **kwargs):
        from ..ps import PsServer
        ep = None
        if self._role_maker is not None:
            eps = self._role_maker.get_pserver_endpoints()
            if eps:
                ep = eps[self._role_maker.server_index() % len(eps)]
        host, port = (ep.rsplit(":", 1) if ep else ("127.0.0.1", "0"))
        self._ps_server = PsServer(host=host, port=int(port))
        return self._ps_server

    def run_server(self):
        """Serve until stop (listen_and_serv_op's blocking loop)."""
        import time
        srv = self._ps_server
        srv.start()
        while srv._running:
            time.sleep(0.05)

    def init_worker(self):
        """Connect this trainer to the pserver(s): sparse rows shard across
        ALL endpoints by id-hash (distribute_transpiler.py:256 key-block
        semantics via ShardedPsClient) and the worker starts heartbeating so
        a dead trainer gets evicted from barriers
        (heart_beat_monitor.h:51)."""
        from ..ps import PsClient, LocalPsEndpoint, ShardedPsClient
        eps = (self._role_maker.get_pserver_endpoints()
               if self._role_maker else [])
        if not eps:
            self._ps_client = LocalPsEndpoint()
        elif len(eps) == 1:
            self._ps_client = PsClient(eps[0])
        else:
            self._ps_client = ShardedPsClient(eps)
        if eps and self._role_maker is not None:
            try:
                self._ps_client.start_heartbeat(
                    self._role_maker.worker_index())
            except Exception:
                pass        # heartbeat is liveness sugar, not a hard dep
        return self._ps_client

    def stop_worker(self):
        client = getattr(self, "_ps_client", None)
        if client is not None:
            client.close()

    @property
    def util(self):
        u = self.__dict__.get("_util")
        if u is None:
            u = self.__dict__["_util"] = _UtilBase(self)
        return u


def _store_gather_bytes(fleet_obj, store, comm_world, tag, payload, me,
                        world):
    """The one store-exchange protocol behind util all_reduce/all_gather:
    generation-scoped prefix + per-comm_world sequence, publish, barrier,
    read all ranks, done-barrier, rank-0 cleanup. Cleanup also removes the
    PREVIOUS call's barrier bookkeeping (everyone is provably past it),
    so per-step use does not grow the store unboundedly."""
    gen = store._restart_generation()
    seqs = fleet_obj.__dict__.setdefault(f"_util_{tag}_seqs", {})
    seq = seqs.get(comm_world, 0)
    seqs[comm_world] = seq + 1
    pre = f"__util{tag}/{gen}/{comm_world}/{seq}"
    store.set(f"{pre}/{me}", payload)
    store.barrier(pre, world)
    parts = [store.get(f"{pre}/{r}") for r in range(world)]
    store.barrier(f"{pre}/done", world)
    if me == 0:
        store.delete_prefix(pre + "/")
        if seq > 0:
            prev = f"__util{tag}/{gen}/{comm_world}/{seq - 1}"
            store.delete_prefix(f"__barrier/{prev}")
    return parts


class _UtilBase:
    """util_factory.py UtilBase parity: cross-process collectives over the
    store, file sharding, FS client slot. State (FS client, sequence
    counters) lives on the Fleet singleton — Fleet.util returns a cached
    instance, but the counters predate that and stay put."""

    def __init__(self, fleet):
        self._fleet = fleet
        self._fs = None

    def _set_file_system(self, fs_client):
        self._fs = fs_client

    def get_file_shard(self, files):
        """util_factory.py:206: contiguous block split of a file list
        across workers (remainder spread over the first ranks)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file names")
        rm = self._fleet._role_maker
        world = rm.worker_num() if rm else 1
        me = rm.worker_index() if rm else 0
        per, rem = divmod(len(files), world)
        begin = per * me + min(me, rem)
        return files[begin:begin + per + (1 if me < rem else 0)]

    def print_on_rank(self, message, rank_id):
        rm = self._fleet._role_maker
        if (rm.worker_index() if rm else 0) == rank_id:
            print(message)

    def all_gather(self, input, comm_world="worker"):
        """Gather one python scalar/array per member, ordered by rank
        (util_factory.py:150). Degrades to [input] before fleet.init()."""
        import pickle
        rm = self._fleet._role_maker
        if rm is None:
            return [input]
        me, world = self._comm_members(comm_world)
        if world <= 1 or me is None:
            return [input]
        parts = _store_gather_bytes(self._fleet, rm._ensure_store(),
                                    comm_world, "ag", pickle.dumps(input),
                                    me, world)
        return [pickle.loads(b) for b in parts]

    def barrier(self, comm_world="worker"):
        self._fleet.barrier_worker()

    def _comm_members(self, comm_world):
        """(my_index, world_size) within the named comm world
        (role_maker _all_comm_world parity: worker / server / all)."""
        rm = self._fleet._role_maker
        wn, sn = rm.worker_num(), max(rm.server_num(), 0)
        if comm_world == "worker":
            return (rm.worker_index() if rm.is_worker() else None), wn
        if comm_world == "server":
            return (rm.server_index() if rm.is_server() else None), sn
        me = rm.worker_index() if rm.is_worker() \
            else wn + rm.server_index()
        return me, wn + sn

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        rm = self._fleet._role_maker
        if not self._fleet._is_collective and rm is not None:
            me, world = self._comm_members(comm_world)
            if world > 1 and me is not None:
                # PS / non-collective mode: the mesh is per-process, so
                # reduce across PROCESSES through the store
                # (gloo_wrapper.h AllReduce)
                return self._store_all_reduce(
                    np.asarray(input.numpy() if isinstance(input, Tensor)
                               else input), mode, comm_world, me, world)
        from ..collective import all_reduce as _ar, ReduceOp
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        t = input if isinstance(input, Tensor) else Tensor(jnp.asarray(input))
        return _ar(t, op=op).numpy()

    def _store_all_reduce(self, arr, mode, comm_world, me, world):
        import pickle
        rm = self._fleet._role_maker
        parts = _store_gather_bytes(self._fleet, rm._ensure_store(),
                                    comm_world, "ar", pickle.dumps(arr),
                                    me, world)
        fn = {"sum": np.sum, "max": np.max, "min": np.min}[mode]
        return fn(np.stack([pickle.loads(b) for b in parts]), axis=0)


fleet = Fleet()
