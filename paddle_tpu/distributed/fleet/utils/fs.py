"""Fleet filesystem clients.

Reference parity: python/paddle/distributed/fleet/utils/fs.py — the FS
abstraction fleet checkpoints/datasets go through: LocalFS (direct posix)
and HDFSClient (shells to ``hadoop fs``). The auto-checkpoint and
save_persistables paths take either; tests use LocalFS, clusters configure
HDFSClient with their hadoop home + configs.
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(RuntimeError):
    pass


class FSFileNotExistsError(RuntimeError):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Posix-direct client (fs.py LocalFS)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def upload(self, local_path, fs_path):
        self.mv(local_path, fs_path, overwrite=True)

    def download(self, fs_path, local_path):
        if os.path.isdir(fs_path):
            shutil.copytree(fs_path, local_path, dirs_exist_ok=True)
        else:
            shutil.copy2(fs_path, local_path)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not overwrite and os.path.exists(dst_path):
            raise FSFileExistsError(dst_path)
        if test_exists and not os.path.exists(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and os.path.exists(dst_path):
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        open(fs_path, "a").close()


class HDFSClient(FS):
    """``hadoop fs`` shell client (fs.py HDFSClient). Needs a hadoop
    install; constructing without one raises with guidance instead of
    failing at first use."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else shutil.which("hadoop")
        if self._hadoop is None or not os.path.exists(self._hadoop):
            raise RuntimeError(
                "HDFSClient needs a hadoop install (pass hadoop_home or put "
                "`hadoop` on PATH); for local filesystems use LocalFS")
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]

    def _run(self, *args, check=True):
        cmd = [self._hadoop, "fs"] + self._cfg + list(args)
        p = subprocess.run(cmd, capture_output=True, text=True)
        if check and p.returncode != 0:
            raise RuntimeError(f"hadoop fs {' '.join(args)}: "
                               f"{p.stderr.strip()[-500:]}")
        return p

    def ls_dir(self, fs_path):
        p = self._run("-ls", fs_path, check=False)
        dirs, files = [], []
        for line in p.stdout.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path, check=False).returncode == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path, check=False).returncode == 0

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def need_upload_download(self):
        return True

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if test_exists and not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        self.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        self._run("-touchz", fs_path)
