"""fleet.utils: filesystem clients + helpers (fleet/utils/ parity)."""
from .fs import (  # noqa: F401
    FS, LocalFS, HDFSClient, FSFileExistsError, FSFileNotExistsError,
)
