"""Elastic training: heartbeats + bounded restart of failed ranks.

Reference parity: python/paddle/distributed/fleet/elastic/ (ElasticManager
watching etcd heartbeats, launch_utils' watch loop) and the heartbeat the
PS HeterPS workers send. Here the out-of-band channel is the fleet
TCPStore: each rank publishes ``__hb/<rank>`` timestamps from a daemon
thread; a monitor (the launcher) flags ranks whose heartbeat goes stale,
and the elastic launch loop restarts dead local processes up to
``max_restarts`` times before tearing the job down (fail-fast is
max_restarts=0).
"""
from __future__ import annotations

import threading
import time


class HeartbeatReporter:
    """Worker side: publish liveness every ``interval`` seconds."""

    def __init__(self, store, rank, interval=5.0):
        self._store = store
        self._rank = rank
        self._interval = interval
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        def run():
            while not self._stop.is_set():
                try:
                    self._store.set(f"__hb/{self._rank}",
                                    repr(time.time()).encode())
                except Exception:
                    pass      # monitor notices staleness; don't crash work
                self._stop.wait(self._interval)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class HeartbeatMonitor:
    """Launcher side: which ranks are stale?

    ``ranks`` generalizes the watched set beyond ``range(world_size)``
    for members that join/leave dynamically — the serving router watches
    replica ids (``replica:<id>``) through the same store keys the
    elastic launcher watches integer ranks through.  ``set_ranks`` is
    safe against a concurrent ``stale_ranks`` scan: the autoscaling
    controller mutates the watched set while the watchdog thread reads
    it, so the swap happens under a lock and scans work on a snapshot.
    """

    def __init__(self, store, world_size=0, stale_after=15.0, ranks=None):
        self._store = store
        self._world = world_size
        self._stale_after = stale_after
        self._lock = threading.Lock()
        self._ranks = None if ranks is None else list(ranks)

    def set_ranks(self, ranks):
        """Replace the watched id set (replica join/evict)."""
        snapshot = list(ranks)
        with self._lock:
            self._ranks = snapshot

    def watched(self):
        with self._lock:
            if self._ranks is not None:
                return list(self._ranks)
        return list(range(self._world))

    def stale_ranks(self):
        now = time.time()
        out = []
        for r in self.watched():
            v = self._store.get(f"__hb/{r}", wait=False)
            if v is None or now - float(v) > self._stale_after:
                out.append(r)
        return out


def _stat_add(name, value=1):
    from ...utils.monitor import stat_add
    stat_add(name, value)


def _stat_set(name, value):
    from ...utils.monitor import stat_set
    stat_set(name, value)


class ElasticLaunch:
    """Bounded-restart supervision of local worker processes
    (fleet/elastic ElasticManager semantics, local scope). Two modes:

    * ``gang=True`` (collective jobs, nprocs > 1 default): ANY nonzero
      exit terminates and respawns the WHOLE gang — a lone restarted rank
      cannot rejoin a live jax.distributed job whose coordinator already
      started, so partial restart would hang the survivors. Matches the
      reference elastic manager's all-or-nothing scale event.
    * ``gang=False`` (independent workers, e.g. PS trainers): only the
      dead rank restarts.
    Exceeding ``max_restarts`` tears the job down (fail-fast is
    max_restarts=0)."""

    def __init__(self, spawn_fn, nprocs, max_restarts=3, poll_s=0.5,
                 gang=None, on_restart=None, monitor=None,
                 watchdog_warmup=30.0):
        self._spawn = spawn_fn     # spawn_fn(local_rank) -> Popen
        self._n = nprocs
        self._max_restarts = max_restarts
        self._poll_s = poll_s
        self._gang = (nprocs > 1) if gang is None else gang
        # called between gang restarts; a launcher owning a store that
        # outlives the workers should clear rendezvous state here, e.g.
        # lambda: store.delete_prefix("__barrier/")
        self._on_restart = on_restart
        # hung-rank watchdog: a HeartbeatMonitor (or a zero-arg factory
        # returning one — lazy, because the store usually lives inside
        # rank 0 and only exists once the gang is up).  A rank whose
        # heartbeat goes stale is treated exactly like a crashed rank:
        # the gang is evicted (SIGKILL — it is by definition not
        # responding) and relaunched under the restart budget.  The
        # warmup window after each (re)spawn gives workers time to reach
        # rendezvous and publish their first heartbeat.
        self._monitor = monitor
        self._watchdog_warmup = watchdog_warmup
        # restart generation, exported to children (spawn_fn closures read
        # it via this attribute or the PADDLE_RESTART_GENERATION env the
        # launcher sets): TCPStore.barrier scopes its keys by it so a
        # half-arrived barrier abandoned by a crashed gang can't skew the
        # restarted gang's rendezvous
        self.generation = 0

    def run(self):
        if self._gang:
            return self._run_gang()
        return self._run_independent()

    def _poll_stale(self, spawned_at):
        """Watchdog poll: ranks whose heartbeat is stale, or [] while the
        watchdog is off / warming up / the store is unreachable (a dead
        store usually means rank 0 died — the process poll catches that;
        the watchdog exists for ranks that are alive-but-hung)."""
        if self._monitor is None:
            return []
        # monotonic: the warmup window is local process time, immune to
        # wall-clock jumps (heartbeat staleness itself stays wall-clocked
        # — those stamps cross processes)
        if time.monotonic() - spawned_at < self._watchdog_warmup:
            return []
        mon = self._monitor() if callable(self._monitor) else self._monitor
        if mon is None:
            return []
        try:
            stale = mon.stale_ranks()
        except Exception:
            return []
        _stat_set("elastic_stale_ranks", len(stale))
        return stale

    def _run_gang(self):
        import signal
        restarts = 0
        while True:
            procs = [self._spawn(i) for i in range(self._n)]
            spawned_at = time.monotonic()
            rc = 0
            while procs:
                time.sleep(self._poll_s)
                for p in list(procs):
                    ret = p.poll()
                    if ret is None:
                        continue
                    procs.remove(p)
                    if ret != 0:
                        rc = ret
                        for q in procs:
                            if q.poll() is None:
                                q.send_signal(signal.SIGTERM)
                        for q in procs:
                            q.wait()
                        procs = []
                        break
                if procs and rc == 0:
                    stale = self._poll_stale(spawned_at)
                    if stale:
                        # hung-rank eviction: the gang is wedged (a live
                        # collective cannot survive a lost member anyway)
                        # — SIGKILL, not SIGTERM: a hung rank may not
                        # service signals, and the crash model under test
                        # is preemption, not graceful shutdown
                        import sys
                        print(f"[elastic] evicting gang: stale ranks "
                              f"{stale} (no heartbeat)", file=sys.stderr)
                        rc = -signal.SIGKILL
                        for q in procs:
                            if q.poll() is None:
                                q.send_signal(signal.SIGKILL)
                        for q in procs:
                            q.wait()
                        procs = []
            if rc == 0:
                return 0, {i: restarts for i in range(self._n)}
            if restarts >= self._max_restarts:
                return rc, {i: restarts for i in range(self._n)}
            restarts += 1
            self.generation = restarts
            _stat_add("elastic_restart_count")
            _stat_set("elastic_restart_generation", self.generation)
            if self._on_restart is not None:
                try:
                    self._on_restart()
                except Exception as e:
                    # a failed reset likely means the respawned gang will
                    # hang at rendezvous — say so instead of hiding it
                    import sys
                    print(f"[elastic] on_restart hook failed: {e!r}; "
                          f"the restarted gang may hang at its barrier",
                          file=sys.stderr)

    def _run_independent(self):
        import signal
        procs = {i: self._spawn(i) for i in range(self._n)}
        restarts = {i: 0 for i in range(self._n)}
        done = set()
        try:
            while len(done) < self._n:
                time.sleep(self._poll_s)
                for i, p in list(procs.items()):
                    if i in done:
                        continue
                    ret = p.poll()
                    if ret is None:
                        continue
                    if ret == 0:
                        done.add(i)
                        continue
                    if restarts[i] < self._max_restarts:
                        restarts[i] += 1
                        _stat_add("elastic_restart_count")
                        procs[i] = self._spawn(i)
                    else:
                        for j, q in procs.items():
                            if j not in done and q.poll() is None:
                                q.send_signal(signal.SIGTERM)
                        return ret, restarts
            return 0, restarts
        except KeyboardInterrupt:
            for q in procs.values():
                if q.poll() is None:
                    q.terminate()
            raise
