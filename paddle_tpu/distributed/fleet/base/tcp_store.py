"""TCP key-value store: multi-host rendezvous + barrier.

Reference parity: the Gloo rendezvous embedded in
python/paddle/distributed/fleet/base/role_maker.py:33 (Gloo HTTP/file
store init + barrier) and the c10d-style TCP store the launcher relies on.
PJRT handles in-slice topology on TPU, but cross-host job bring-up still
needs an out-of-band store: rank 0 serves a tiny length-prefixed
set/get/wait/add protocol; other ranks connect. Barriers are implemented
with an atomic add + wait-for-count key, matching the reference's
barrier-on-store semantics.
"""
from __future__ import annotations

import random
import socket
import struct
import threading
import time


def _send_msg(sock, *parts: bytes):
    payload = struct.pack("<I", len(parts))
    for p in parts:
        payload += struct.pack("<I", len(p)) + p
    sock.sendall(payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    parts = []
    for _ in range(n):
        (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
        parts.append(_recv_exact(sock, ln))
    return parts


class _Server(threading.Thread):
    def __init__(self, port):
        super().__init__(daemon=True)
        self._kv = {}
        # add-dedup ledger: req_id -> cached reply.  add is the one
        # non-idempotent op; a client retrying after a lost reply resends
        # the SAME req_id and gets the recorded result instead of
        # double-counting (which would skew barrier arrival windows).
        self._applied = {}
        self._applied_order = []
        self._cv = threading.Condition()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen(64)
        self._stop = False

    def run(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                cmd, *args = _recv_msg(conn)
                try:
                    self._handle(conn, cmd, args)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:
                    # malformed request (e.g. add on a non-int value):
                    # reply with a diagnostic instead of killing the
                    # connection thread and leaving the client hanging
                    _send_msg(conn, b"err", repr(e).encode())
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _handle(self, conn, cmd, args):
        # every reply leads with b"ok"/b"err" so clients can distinguish
        # payloads from error diagnostics unambiguously
        if cmd == b"set":
            with self._cv:
                self._kv[args[0]] = args[1]
                self._cv.notify_all()
            _send_msg(conn, b"ok")
        elif cmd == b"get":
            with self._cv:
                v = self._kv.get(args[0])
            _send_msg(conn, b"ok", v if v is not None else b"",
                      b"1" if v is not None else b"0")
        elif cmd == b"add":
            req_id = args[2] if len(args) > 2 else None
            with self._cv:
                if req_id is not None and req_id in self._applied:
                    cur = self._applied[req_id]     # retried: replay reply
                else:
                    cur = int(self._kv.get(args[0], b"0")) + int(args[1])
                    self._kv[args[0]] = str(cur).encode()
                    if req_id is not None:
                        self._applied[req_id] = cur
                        self._applied_order.append(req_id)
                        while len(self._applied_order) > 4096:
                            self._applied.pop(
                                self._applied_order.pop(0), None)
                    self._cv.notify_all()
            _send_msg(conn, b"ok", str(cur).encode())
        elif cmd == b"delprefix":
            with self._cv:
                dead = [k for k in self._kv if k.startswith(args[0])]
                for k in dead:
                    del self._kv[k]
            _send_msg(conn, b"ok", str(len(dead)).encode())
        elif cmd == b"wait":
            key, timeout = args[0], float(args[1])
            # monotonic deadlines throughout: a wall-clock jump must not
            # spuriously expire (or extend) a rendezvous wait
            deadline = time.monotonic() + timeout
            with self._cv:
                while key not in self._kv:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._cv.wait(left):
                        break
                ok = key in self._kv
            _send_msg(conn, b"ok", b"1" if ok else b"0")
        else:
            _send_msg(conn, b"err", b"unknown command")

    def shutdown(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass


class TCPStore:
    """c10d-style store. Rank 0 passes is_master=True and serves.

    Client hardening (ISSUE 3): every op retries transient socket
    failures (ECONNRESET, timeouts, a bounced server) with exponential
    backoff + jitter, RECONNECTING between attempts — a reply lost
    mid-flight desyncs the length-prefixed protocol, so the old
    connection is never reused after an error.  Retry budget comes from
    ``FLAGS_store_max_retries`` / ``FLAGS_store_retry_backoff``; the
    deterministic fault harness (testing/faults.py ``store_drop``
    clauses) injects drops right before the send to exercise this path.
    """

    def __init__(self, host, port, world_size=1, is_master=False,
                 timeout=120.0):
        self._timeout = timeout
        self._server = None
        if is_master:
            self._server = _Server(port)
            self._server.start()
            port = self._server.port
        self.host, self.port = host, port
        self._sock = None
        self._connect(timeout)
        self._lock = threading.Lock()

    def _connect(self, budget=None):
        """(Re)establish the client connection, retrying refusals until
        ``budget`` seconds elapse (a restarting master needs a moment to
        re-listen)."""
        deadline = time.monotonic() + (budget if budget is not None
                                       else self._timeout)
        last = None
        while True:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=self._timeout)
                return
            except OSError as e:
                last = e
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"store at {self.host}:{self.port} unreachable: "
                        f"{last}")
                time.sleep(0.05)

    def _reconnect(self):
        """Drop the (possibly desynced) connection and start a clean one:
        the length-prefixed protocol has no resync point mid-stream, so
        after ANY client-side error the only safe recovery is a fresh
        socket — which also restores the default timeout."""
        try:
            self._sock.close()
        except OSError:
            pass
        self._connect()

    def _retry_budget(self):
        from ....framework import flags as _flags
        return (int(_flags.flag("store_max_retries")),
                float(_flags.flag("store_retry_backoff")))

    def _maybe_inject_drop(self, op: str):
        from ....testing.faults import active_plan
        plan = active_plan()
        if plan is not None and plan.should_drop_store_op(op):
            try:
                self._sock.close()      # next send/recv fails -> retry path
            except OSError:
                pass

    def _request(self, op: str, make_parts, reply_timeout=None):
        """One store round-trip with the retry/reconnect policy.
        ``make_parts`` is re-evaluated per attempt (wait shrinks its
        remaining time); ``reply_timeout`` likewise callable-or-None.
        Server-side "err" replies (RuntimeError) are NOT retried —
        they're malformed requests, not transport faults."""
        retries, base = self._retry_budget()
        attempt = 0
        while True:
            self._maybe_inject_drop(op)
            try:
                with self._lock:
                    t = reply_timeout() if callable(reply_timeout) \
                        else reply_timeout
                    if t is not None:
                        self._sock.settimeout(t)
                    try:
                        _send_msg(self._sock, *make_parts())
                        return self._reply()
                    finally:
                        if t is not None:
                            try:
                                self._sock.settimeout(self._timeout)
                            except OSError:
                                pass    # dead socket: reconnect handles it
            except (ConnectionError, OSError):
                # transport fault: the stream may hold a half-read or
                # late reply — resync by reconnecting, even on the final
                # attempt (the NEXT call must start clean)
                with self._lock:
                    try:
                        self._reconnect()
                    except ConnectionError:
                        if attempt >= retries:
                            raise
                if attempt >= retries:
                    raise
                delay = base * (2 ** attempt)
                time.sleep(delay + random.uniform(0, delay * 0.5))
                attempt += 1

    def _reply(self):
        parts = _recv_msg(self._sock)
        if parts and parts[0] == b"err":
            raise RuntimeError(f"store error: "
                               f"{parts[1].decode() if len(parts) > 1 else '?'}")
        if not parts or parts[0] != b"ok":
            raise ConnectionError("store protocol desync")
        return parts[1:]

    def set(self, key: str, value: bytes):
        payload = value if isinstance(value, bytes) else str(value).encode()
        self._request("set",
                      lambda: (b"set", key.encode(), payload))

    def get(self, key: str, wait=True):
        if wait and not self.wait(key, self._timeout):
            raise TimeoutError(f"store key {key!r} never set")
        v, present = self._request("get", lambda: (b"get", key.encode()))
        return v if present == b"1" else None

    def add(self, key: str, amount: int = 1) -> int:
        import os
        # one req_id per LOGICAL add, constant across retries: the server
        # dedups it, so a lost-reply resend can't double-count
        req_id = os.urandom(8)
        (v,) = self._request("add", lambda: (b"add", key.encode(),
                                             str(amount).encode(), req_id))
        return int(v)

    def delete_prefix(self, prefix: str) -> int:
        """Delete every key starting with ``prefix``; returns the count."""
        (n,) = self._request("delprefix",
                             lambda: (b"delprefix", prefix.encode()))
        return int(n)

    def reset_barrier(self, name: str = ""):
        """Clear barrier count/release keys across ALL generations (all
        barriers when ``name`` is empty). An elastic launcher whose store
        outlives workers calls this between gang restarts so a
        half-arrived (abandoned) barrier can't skew the counters."""
        self.delete_prefix(f"__barrier/{name}/" if name else "__barrier/")

    def bump_restart_generation(self) -> int:
        """Advance the store-resident restart generation that scopes every
        barrier key. The restarting supervisor calls this ONCE before
        respawning a gang; all hosts' workers then agree on the new
        generation regardless of how many times each host restarted
        locally (the per-host PADDLE_RESTART_GENERATION env is only the
        fallback when this key has never been bumped)."""
        return self.add("__restart_generation", 1)

    def _restart_generation(self) -> str:
        v = self.get("__restart_generation", wait=False)
        if v is not None:
            return v.decode()
        import os
        return os.environ.get("PADDLE_RESTART_GENERATION", "0")

    def wait(self, key: str, timeout: float = None) -> bool:
        t = timeout or self._timeout
        deadline = time.monotonic() + t
        # the server's wait deadline starts when it RECEIVES the request;
        # the socket recv timeout must outlive it or the late '0' reply
        # desyncs the connection protocol.  Hardening: each retry re-sends
        # wait with only the REMAINING time (the overall deadline is the
        # caller's contract), and any mid-wait transport error — reply
        # lost, server bounced — reconnects inside _request, so neither
        # the inflated t+30 timeout nor a desynced stream can leak into
        # the next call.
        left = lambda: max(0.1, deadline - time.monotonic())  # noqa: E731
        (ok,) = self._request("wait",
                              lambda: (b"wait", key.encode(),
                                       str(left()).encode()),
                              reply_timeout=lambda: left() + 30.0)
        return ok == b"1"

    def barrier(self, name: str, world_size: int, timeout: float = None):
        """All ranks add 1 to the barrier key, then wait for the release
        key the last arriver sets (Gloo barrier-on-store parity).

        Reuse safety is two-layered:

        * a *restart generation* prefixes every key — the store-resident
          value bumped by :meth:`bump_restart_generation` (shared across
          hosts), falling back to ``PADDLE_RESTART_GENERATION`` (set per
          host by the elastic launcher) — so a half-arrived barrier
          abandoned by a crashed gang can never skew the restarted gang's
          counters;
        * within a generation the counter is never reset, so a reused
          barrier name lands in a fresh *arrival window*: arrival ``n``
          belongs to window ``(n-1)//world_size`` and waits on that
          window's release key — a stale release from a previous complete
          use never releases it early.

        A launcher owning a store that outlives workers can also clear
        state explicitly via :meth:`reset_barrier`.
        """
        rg = self._restart_generation()
        n = self.add(f"__barrier/{name}/g{rg}/count", 1)
        gen = (n - 1) // world_size
        arrived = n - gen * world_size
        release = f"__barrier/{name}/g{rg}/release/{gen}"
        if arrived >= world_size:
            self.set(release, b"1")
        if not self.wait(release, timeout or self._timeout):
            raise TimeoutError(f"barrier {name!r} timed out ({arrived}/"
                               f"{world_size} arrived)")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
        if self._server is not None:
            self._server.shutdown()
