"""RoleMaker: cluster topology from environment.

Reference parity: python/paddle/distributed/fleet/base/role_maker.py —
PaddleCloudRoleMaker (:528) parses PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS (+ PS env), UserDefinedRoleMaker (:875).  The Gloo
rendezvous embedded there (:33) is unnecessary on TPU: PJRT discovers the
slice topology; multi-host barriers ride jax.distributed.
"""
from __future__ import annotations

import os
from enum import Enum


class Role(Enum):
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self.worker_index() == 0

    def worker_index(self):
        raise NotImplementedError

    def worker_num(self):
        raise NotImplementedError


class PaddleCloudRoleMaker(RoleMakerBase):
    """role_maker.py:528 parity; trusts env (so tests fake any topology)."""

    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._worker_index = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._worker_num = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = eps.split(",") if eps else ["127.0.0.1:0"]
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT",
                                           self._worker_endpoints[0])
        pserver = os.getenv("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = pserver.split(",") if pserver else []
        training_role = os.getenv("TRAINING_ROLE", "TRAINER")
        self._role = Role.SERVER if training_role == "PSERVER" else Role.WORKER

    def worker_index(self):
        return self._worker_index

    def worker_num(self):
        return self._worker_num

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def server_num(self):
        return len(self._server_endpoints)

    def server_index(self):
        return int(os.getenv("PADDLE_PORT_INDEX", "0"))

    def _get_trainer_id(self):
        return self._worker_index

    def _is_collective(self):
        return self._is_collective

    # -- rendezvous / barrier (Gloo-store parity, role_maker.py:33) ----------
    def _store_endpoint(self):
        ep = os.getenv("PADDLE_STORE_ENDPOINT")
        if ep:
            host, port = ep.rsplit(":", 1)
            return host, int(port)
        # default: rank 0's trainer endpoint host, side-channel port
        host = self._worker_endpoints[0].rsplit(":", 1)[0] or "127.0.0.1"
        port = int(os.getenv("PADDLE_STORE_PORT", "61001"))
        return host, port

    def _ensure_store(self, timeout=120.0):
        if getattr(self, "_store", None) is None:
            from .tcp_store import TCPStore
            host, port = self._store_endpoint()
            self._store = TCPStore(
                "127.0.0.1" if self.is_first_worker() else host, port,
                world_size=self._worker_num,
                is_master=self.is_first_worker(), timeout=timeout)
            self._maybe_start_heartbeat()
        return self._store

    def _maybe_start_heartbeat(self):
        """Elastic liveness: when the launcher runs a hung-rank watchdog
        it exports ``PADDLE_ELASTIC_HEARTBEAT_S``; every worker then
        publishes ``__hb/<rank>`` from a daemon thread as soon as it has
        a store (fleet init / rendezvous)."""
        interval = float(os.getenv("PADDLE_ELASTIC_HEARTBEAT_S", "0") or 0)
        if interval <= 0 or getattr(self, "_heartbeat", None) is not None:
            return
        from ..elastic import HeartbeatReporter
        self._heartbeat = HeartbeatReporter(
            self._store, self._worker_index, interval=interval).start()

    def rendezvous(self, timeout=120.0):
        """Exchange endpoints through the store and wait for the full
        cluster: returns the ordered endpoint list once every rank has
        registered."""
        store = self._ensure_store(timeout)
        store.set(f"__ep/{self._worker_index}",
                  self._current_endpoint.encode())
        eps = []
        for r in range(self._worker_num):
            if not store.wait(f"__ep/{r}", timeout):
                raise TimeoutError(
                    f"rendezvous: rank {r} never registered within "
                    f"{timeout}s")
            eps.append(store.get(f"__ep/{r}", wait=False).decode())
        self._worker_endpoints = eps
        return eps

    def barrier(self, comm_world="worker", timeout=None):
        """Cluster-wide barrier over the store (_barrier parity)."""
        if self._worker_num <= 1:
            return
        if not hasattr(self, "_barrier_seq"):
            self._barrier_seq = {}
        seq = self._barrier_seq.get(comm_world, 0)
        self._barrier_seq[comm_world] = seq + 1
        self._ensure_store().barrier(f"{comm_world}/{seq}",
                                     self._worker_num, timeout)


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """role_maker.py:875 parity: explicit topology."""

    def __init__(self, is_collective=False, current_id=0, role=Role.WORKER,
                 worker_num=1, worker_endpoints=None, server_endpoints=None,
                 **kwargs):
        super().__init__(is_collective=is_collective)
        self._worker_index = current_id
        self._worker_num = worker_num
        self._worker_endpoints = worker_endpoints or ["127.0.0.1:0"]
        self._server_endpoints = server_endpoints or []
        self._role = role
