"""Strategy-knob ledger: every DistributedStrategy field is accounted for.

Reference parity: the reference's strategy compiler
(fleet/base/strategy_compiler.py + meta_optimizers/) ACTS on every enabled
flag or errors; silently-inert knobs are a correctness trap for ported
scripts (VERDICT r2 Weak #6). This ledger records, for each field, how the
TPU engine honors it:

  engine  — translated into the compiled SPMD step (see mapping)
  n/a     — subsumed by XLA/GSPMD; enabling it is a no-op BY DESIGN, with
            the reason recorded here
  raises  — not supported in this engine; enabling it raises loudly

tests/test_meta_optimizers.py asserts the ledger is total: every strategy
field is classified, and every 'engine' flag observably changes the step
options while every 'raises' flag raises.
"""
from __future__ import annotations

LEDGER = {
    # field -> (kind, note)
    "amp": ("engine", "compute_dtype=bf16 (or fp16) in the jitted step"),
    "recompute": ("engine", "jax.checkpoint over the loss (remat=True)"),
    "sharding": ("engine", "ZeRO stage via zero=stage param/grad/opt layouts"),
    "pipeline": ("engine", "pp mesh axis + GPipe microbatch schedule"),
    "tensor_parallel": ("engine", "mp mesh axis degree at fleet.init"),
    "sequence_parallel": ("engine", "sp mesh axis + ring attention"),
    "gradient_merge": ("engine", "accumulate_steps microbatch scan"),
    "localsgd": ("engine", "per-rank replicas + periodic mean "
                           "(TrainStep localsgd_k/localsgd_begin)"),
    "lamb": ("engine", "optimizer swapped to Lamb at distributed_optimizer"),
    "lars": ("engine", "optimizer swapped to Lars at distributed_optimizer"),
    "a_sync": ("engine", "PS-mode async communicator (ps/ package; the "
                         "collective TrainStep path rejects it)"),
    "dgc": ("engine", "deep gradient compression as an engine mode "
                      "(TrainStep dgc_sparsity/dgc_rampup_begin): per-rank "
                      "momentum correction + residual top-k before the "
                      "cross-rank mean; rampup phase IS dense Momentum. "
                      "NB: on-chip ICI makes dense bf16 allreduce faster "
                      "at every scale measured — dgc is for DCN-bound "
                      "multi-host jobs"),
    "fp16_allreduce": ("n/a", "grads already travel in bf16 when amp is on; "
                              "XLA fuses the cast into the reduce"),
    "fuse_all_reduce_ops": ("n/a", "XLA's all-reduce combiner fuses "
                                   "collectives (xla_tpu_* combiner flags)"),
    "fuse_grad_size_in_MB": ("n/a", "XLA combiner threshold; fixed by the "
                                    "compiler, not per-job"),
    "hierarchical_allreduce": ("n/a", "GSPMD emits ICI/DCN-aware reductions "
                                      "from the mesh topology"),
    "hierarchical_allreduce_inter_nranks": ("n/a", "see "
                                                   "hierarchical_allreduce"),
    "nccl_comm_num": ("n/a", "no NCCL; PJRT owns collective channels"),
    "sync_nccl_allreduce": ("n/a", "XLA schedules collectives; no separate "
                                   "comm stream to sync"),
    "cudnn_exhaustive_search": ("n/a", "no cuDNN; XLA picks conv tilings"),
    "cudnn_batchnorm_spatial_persistent": ("n/a", "no cuDNN"),
    "conv_workspace_size_limit": ("n/a", "no cuDNN workspace on TPU"),
    "sync_batch_norm": ("engine", "fleet.distributed_model converts BN "
                                  "layers via SyncBatchNorm."
                                  "convert_sync_batchnorm (global stats "
                                  "through GSPMD's cross-dp reduction)"),
    "find_unused_parameters": ("n/a", "jax.grad prunes unused params "
                                      "structurally; no reducer hooks to "
                                      "miss"),
    "last_comm_group_size_MB": ("n/a", "XLA combiner concern"),
}


def check_strategy(strategy):
    """Raise for any enabled flag the engine does not honor."""
    for field, (kind, note) in LEDGER.items():
        try:
            enabled = bool(getattr(strategy, field))
        except AttributeError:
            continue
        if enabled and kind == "raises":
            raise NotImplementedError(
                f"DistributedStrategy.{field} is not supported by the TPU "
                f"engine: {note}")
    return True
