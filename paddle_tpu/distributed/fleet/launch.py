"""Launch CLI: ``python -m paddle_tpu.distributed.fleet.launch train.py``.

Reference parity: python/paddle/distributed/fleet/launch.py:321 —
launch_collective (:198) spawns one process per GPU with PADDLE_TRAINER_ID /
endpoints env and watches children (launch_utils.py:451,517).

TPU-native: the process unit is a *host*, not a chip (PJRT owns all local
chips).  On a single host this launcher therefore spawns ONE training
process by default; --nproc_per_node>1 exists for CPU-simulated cluster
tests, mirroring how the reference's own test suite fakes topology
(SURVEY.md §4.3).  Fail-fast watching matches launch_utils.py:517: any child
death tears the job down.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_ports(n):
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.fleet.launch")
    p.add_argument("--ips", default="127.0.0.1",
                   help="comma-separated host ips")
    p.add_argument("--host_rank", type=int,
                   default=int(os.getenv("PADDLE_HOST_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 on TPU: PJRT owns all chips)")
    p.add_argument("--started_port", type=int, default=None)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0 = fail-fast (default); 1 = restart dead local "
                        "ranks up to --max_restarts (fleet/elastic parity)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--elastic_stale_after", type=float, default=0.0,
                   help="hung-rank watchdog: evict and restart the gang "
                        "when a rank's heartbeat is older than this many "
                        "seconds (0 = watchdog off). Workers auto-start "
                        "HeartbeatReporters via PADDLE_ELASTIC_HEARTBEAT_S")
    p.add_argument("--elastic_watchdog_warmup", type=float, default=30.0,
                   help="seconds after each (re)spawn before the watchdog "
                        "starts judging heartbeats (workers need to reach "
                        "rendezvous first)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster(ips, nproc_per_node, started_port=None):
    """launch.py:257 parity: (endpoints, world_size)."""
    hosts = ips.split(",")
    nranks = len(hosts) * nproc_per_node
    ports = ([started_port + i for i in range(nproc_per_node)]
             if started_port else _free_ports(nproc_per_node))
    endpoints = [f"{h}:{p}" for h in hosts for p in ports]
    return endpoints, nranks


def launch_collective(args):
    endpoints, nranks = get_cluster(args.ips, args.nproc_per_node,
                                    args.started_port)
    log_fps = []
    base_rank = args.host_rank * args.nproc_per_node
    supervisor = []   # filled when elastic supervision is active

    def spawn(local):
        rank = base_rank + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nranks),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "FLAGS_selected_tpus": str(local),
            # gang-restart generation: scopes TCPStore barrier keys so an
            # abandoned half-arrived barrier can't skew the new gang
            "PADDLE_RESTART_GENERATION": str(
                supervisor[0].generation if supervisor else 0),
        })
        if args.elastic_level >= 1 and args.elastic_stale_after > 0:
            # workers publish heartbeats at ~1/3 the staleness horizon
            env["PADDLE_ELASTIC_HEARTBEAT_S"] = str(
                max(args.elastic_stale_after / 3.0, 0.5))
        cmd = [sys.executable, "-u", args.training_script] + \
            args.training_script_args
        out = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            # append only under elastic supervision (restart logs belong
            # together); plain runs truncate like the reference launcher
            mode = "a" if args.elastic_level >= 1 else "w"
            out = open(os.path.join(args.log_dir, f"workerlog.{local}"),
                       mode)
            log_fps.append(out)
        return subprocess.Popen(cmd, env=env, stdout=out, stderr=out)

    try:
        if args.elastic_level >= 1:
            # bounded-restart supervision (fleet/elastic parity)
            from .elastic import ElasticLaunch
            monitor = None
            if args.elastic_stale_after > 0:
                # lazy: the store lives inside rank 0, so the monitor's
                # client connection can only be made once a gang is up —
                # and must be retried if it isn't yet
                state = {}

                def monitor(_state=state):
                    if "m" in _state:
                        return _state["m"]
                    try:
                        from .base.tcp_store import TCPStore
                        from .elastic import HeartbeatMonitor
                        ep = os.getenv("PADDLE_STORE_ENDPOINT")
                        if ep:
                            host, port = ep.rsplit(":", 1)
                            port = int(port)
                        else:
                            host = (endpoints[0].rsplit(":", 1)[0]
                                    or "127.0.0.1")
                            port = int(os.getenv("PADDLE_STORE_PORT",
                                                 "61001"))
                        store = TCPStore(host, port, timeout=2.0)
                        _state["m"] = HeartbeatMonitor(
                            store, nranks,
                            stale_after=args.elastic_stale_after)
                    except Exception:
                        return None
                    return _state["m"]
            # collective jobs are always gangs, even at 1 proc per host:
            # a lone restarted rank cannot rejoin collectives mid-flight
            el = ElasticLaunch(spawn, args.nproc_per_node,
                               max_restarts=args.max_restarts, gang=True,
                               monitor=monitor,
                               watchdog_warmup=args.elastic_watchdog_warmup)
            supervisor.append(el)
            rc, restarts = el.run()
            if any(restarts.values()):
                print(f"[launch] restarts per rank: {restarts}",
                      file=sys.stderr)
            return rc
        # watch_local_trainers (launch_utils.py:517) parity: fail-fast
        procs = [spawn(local) for local in range(args.nproc_per_node)]
        rc = 0
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0:
                    rc = ret
                    for q in procs:
                        q.send_signal(signal.SIGTERM)
                    procs = []
                    break
            time.sleep(0.5)
        return rc
    finally:
        for f in log_fps:
            f.close()


def launch(argv=None):
    args = _parse_args(argv)
    sys.exit(launch_collective(args))


if __name__ == "__main__":
    launch()
