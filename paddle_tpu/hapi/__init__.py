"""paddle.hapi parity: high-level Model API + callbacks."""
from .model import Model  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
    ReduceLROnPlateau, VisualDL,
)
from .summary import summary  # noqa: F401
