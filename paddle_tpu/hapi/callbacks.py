"""hapi callbacks (python/paddle/hapi/callbacks.py parity): Callback base,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler."""
from __future__ import annotations

import os
import sys
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params.update(params or {})

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        # monotonic: an NTP step mid-epoch must not bend the ms/step rate
        self._t0 = time.monotonic()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and (step + 1) % self.log_freq == 0:
            logs = logs or {}
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}" for k, v in logs.items())
            total = f"/{self.steps}" if self.steps else ""
            dt = time.monotonic() - self._t0
            print(f"step {step + 1}{total} - {dt * 1000 / (step + 1):.0f}"
                  f"ms/step - {items}")
            sys.stdout.flush()

    def on_eval_end(self, logs=None):
        if self.verbose:
            logs = logs or {}
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}" for k, v in logs.items())
            print(f"Eval - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -float("inf")
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")

    def on_eval_end(self, logs=None):
        logs = logs or {}
        # Model.fit emits eval logs as 'eval_loss'/'eval_<metric>'; accept
        # the paddle-style bare names ('loss', 'acc') transparently
        cur = logs.get(self.monitor, logs.get(f"eval_{self.monitor}"))
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None) if opt else None
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class VisualDL(Callback):
    """hapi VisualDL callback parity (python/paddle/hapi/callbacks.py
    VisualDL) over utils.monitor.LogWriter: logs per-step train metrics
    and per-epoch eval metrics as scalar curves."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._writer = None
        self._step = 0

    def _w(self):
        if self._writer is None:
            from ..utils.monitor import LogWriter
            self._writer = LogWriter(self.log_dir)
        return self._writer

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"train/{k}", float(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"eval/{k}", float(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()
            self._writer = None   # a later fit() reopens a fresh file


class ReduceLROnPlateau(Callback):
    """hapi/callbacks.py ReduceLROnPlateau parity: monitor an eval metric;
    after ``patience`` epochs without improvement multiply the optimizer's
    (float) learning rate by ``factor``, then hold for ``cooldown``
    epochs.  'auto' mode infers direction from the monitor name ('acc' →
    max)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau does not support a "
                             "factor >= 1.0.")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._reset()

    def _reset(self):
        import warnings
        if self.mode not in ("auto", "min", "max"):
            warnings.warn(f"Learning rate reduction mode {self.mode} is "
                          "unknown, fallback to auto mode.")
            self.mode = "auto"
        if self.mode == "min" or (self.mode == "auto"
                                  and "acc" not in self.monitor):
            self.better = lambda a, b: a < b - self.min_delta
            self.best = float("inf")
        else:
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -float("inf")
        self.cooldown_counter = 0
        self.wait = 0

    def on_train_begin(self, logs=None):
        self._reset()

    def on_eval_end(self, logs=None):
        import warnings
        logs = logs or {}
        cur = logs.get(self.monitor, logs.get(f"eval_{self.monitor}"))
        if cur is None:
            warnings.warn("Monitor of ReduceLROnPlateau should be loss "
                          "or metric name.")
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                if not isinstance(getattr(opt, "_learning_rate", None),
                                  (int, float)):
                    # reference behavior: an LRScheduler owns the lr —
                    # warn and leave it alone instead of aborting fit()
                    warnings.warn(
                        "Expected learning_rate be float, but got "
                        f"{type(getattr(opt, '_learning_rate', None))}.")
                    return
                old = float(opt.get_lr())
                new = max(old * self.factor, self.min_lr)
                if old - new > 1e-12:
                    opt.set_lr(new)
                    if self.verbose:
                        print(f"Epoch: reducing learning rate from {old} "
                              f"to {new}.")
                self.cooldown_counter = self.cooldown
                self.wait = 0
