"""paddle.Model: the Keras-like high-level API.

Reference parity: python/paddle/hapi/model.py — Model (:809) with
prepare/fit/evaluate/predict/save/load (:1041,:1242,:1297,:1513) and dual
static/dygraph adapters (:263,:641).

TPU-first: there is ONE adapter — the compiled sharded TrainStep
(parallel/train_step.py). fit() compiles the whole train step (forward +
loss + backward + optimizer) once and streams DataLoader batches into it;
evaluate/predict ride the jitted EvalStep. The reference's per-mode
train_batch/eval_batch surface is kept.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..framework.tensor import Tensor
from ..framework.io_state import save as _save, load as _load
from ..metric import Metric
from .callbacks import Callback, CallbackList, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._eval_step = None
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        self._amp = amp_configs
        self._train_step = None
        self._eval_step = None
        return self

    def _ensure_train_step(self):
        if self._train_step is None:
            from ..parallel.train_step import TrainStep
            import jax.numpy as jnp
            opts = {}
            opt = self._optimizer
            if hasattr(opt, "build_train_step"):  # fleet DistributedOptimizer
                self._train_step = opt.build_train_step(
                    self.network, self._loss)
                return self._train_step
            if self._amp:
                level = self._amp if isinstance(self._amp, str) else "O1"
                if level in ("O1", "O2"):
                    opts["compute_dtype"] = jnp.bfloat16
            self._train_step = TrainStep(self.network, opt, self._loss,
                                         **opts)
        return self._train_step

    def _ensure_eval_step(self):
        if self._eval_step is None:
            from ..parallel.train_step import EvalStep
            self._eval_step = EvalStep(self.network, loss_fn=self._loss)
        return self._eval_step

    # -- batch-level API -----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        step = self._ensure_train_step()
        loss = step(inputs, labels)
        return [float(loss)]

    def eval_batch(self, inputs, labels=None):
        self._sync_from_train()
        return self._eval_batch_nosync(inputs, labels)

    def _eval_batch_nosync(self, inputs, labels=None):
        step = self._ensure_eval_step()
        res = step(inputs, labels)
        if self._loss is not None:
            out, loss = res  # EvalStep with loss_fn returns (out, loss)
        else:
            out, loss = res, None
        metrics = []
        for m in self._metrics:
            first = out[0] if isinstance(out, (tuple, list)) else out
            m.update(m.compute(first, labels)) if _unary_update(m) \
                else m.update(first, labels)
            metrics.append(m.accumulate())
        return ([float(loss)] if loss is not None else []) + metrics

    def predict_batch(self, inputs):
        self._sync_from_train()
        return self._predict_batch_nosync(inputs)

    def _predict_batch_nosync(self, inputs):
        step = self._ensure_eval_step()  # reuse the jitted forward
        out = step(inputs)
        if self._loss is not None:  # EvalStep with loss_fn returns (out, loss)
            out = out[0]
        return out

    def _sync_from_train(self):
        if self._train_step is not None and self._train_step._state is not None:
            self._train_step.sync_to_layer()
            if self._eval_step is not None:
                # the eager layer just changed under the EvalStep's
                # device-resident snapshot — drop it so eval sees the
                # freshly trained weights
                self._eval_step.invalidate()

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, checkpoint_dir=None,
            checkpoint_every_n_steps=0):
        from ..io import DataLoader, Dataset
        # step-level fault tolerance (paddle_tpu.checkpoint): atomic,
        # checksummed, async step checkpoints + auto-resume.  Unlike
        # ``save_dir`` (epoch-end eager save() files), these are the
        # compiled TrainStep's full state — params, optimizer
        # accumulators, BN buffers and the step counter — written with
        # the manifest-commit-last protocol, so a preempted run restarts
        # from the newest COMPLETE step instead of epoch 0.
        if checkpoint_dir:
            from ..checkpoint import CheckpointManager
            tstep = self._ensure_train_step()
            tstep.attach_checkpoint_manager(
                CheckpointManager(checkpoint_dir, async_save=True))
            try:
                tstep.restore_from_checkpoint()
            except FileNotFoundError:
                pass                    # fresh run
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        cbks = CallbackList([ProgBarLogger(log_freq, verbose=verbose)]
                            + (callbacks or []))
        cbks.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks.set_params({"epochs": epochs, "steps": steps, "verbose": verbose})
        self.stop_training = False

        cbks.on_train_begin()
        it = 0
        logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            logs = {}
            for step_i, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step_i)
                inputs, labels = _split_batch(batch)
                loss = self.train_batch(inputs, labels)
                logs = {"loss": loss[0]}
                cbks.on_train_batch_end(step_i, logs)
                it += 1
                if checkpoint_dir and checkpoint_every_n_steps and \
                        it % checkpoint_every_n_steps == 0:
                    self._train_step.save_checkpoint()
                if num_iters is not None and it >= num_iters:
                    break
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _as_logs=True)
                logs.update(eval_logs)
                cbks.on_eval_end(eval_logs)
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training or (num_iters is not None
                                      and it >= num_iters):
                break
        cbks.on_train_end(logs)
        if checkpoint_dir:
            # final step checkpoint; wait=True also fences any in-flight
            # async save so fit() never returns with an uncommitted write
            self._train_step.save_checkpoint(wait=True)
        if save_dir:
            self.save(os.path.join(save_dir, "final"))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _as_logs=False):
        from ..io import DataLoader, Dataset
        loader = DataLoader(eval_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        self._sync_from_train()  # once, not per batch
        for batch in loader:
            inputs, labels = _split_batch(batch)
            vals = self._eval_batch_nosync(inputs, labels)
            if self._loss is not None and vals:
                losses.append(vals[0])
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[f"eval_{m.name()}"] = m.accumulate()
        if verbose:
            print(" - ".join(f"{k}: {v}" for k, v in logs.items()))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset
        loader = DataLoader(test_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(test_data, Dataset) else test_data
        self._sync_from_train()  # once, not per batch
        per_output = None
        for batch in loader:
            inputs, _ = _split_batch(batch)
            out = self._predict_batch_nosync(inputs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            if per_output is None:
                per_output = [[] for _ in outs]
            for slot, o in zip(per_output, outs):
                slot.append(o.numpy())
        per_output = per_output or [[]]
        if stack_outputs:
            return [np.concatenate(slot) for slot in per_output]
        return per_output

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        self._sync_from_train()
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))
        self._train_step = None
        self._eval_step = None

    # -- decoding ------------------------------------------------------------
    def generate(self, input_ids, **kwargs):
        """Autoregressive decoding through the network's static-shape
        KV-cache incremental forward (text.generation.generate): one
        prefill executable + one scanned decode executable, zero
        per-token compiles.  The network must implement the
        init_cache/forward_cached contract (e.g. text.models.GPTModel)."""
        self._sync_from_train()
        from ..text.generation import generate as _generate
        return _generate(self.network, input_ids, **kwargs)

    # -- misc ----------------------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        return summary(self.network, input_size, dtype)


def _split_batch(batch):
    if isinstance(batch, (tuple, list)):
        if len(batch) == 1:
            return batch[0], None
        if len(batch) == 2:
            return batch[0], batch[1]
        return tuple(batch[:-1]), batch[-1]
    return batch, None


def _unary_update(m):
    """Accuracy.update takes the precomputed `correct` tensor; other metrics
    take (pred, label) — match hapi's compute/update split."""
    return isinstance(m, __import__(
        "paddle_tpu.metric", fromlist=["Accuracy"]).Accuracy)
