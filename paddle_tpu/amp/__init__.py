"""paddle.amp: automatic mixed precision.

Reference parity: python/paddle/fluid/dygraph/amp/auto_cast.py:91 (amp_guard
with white/black op lists) and loss_scaler.py:27 (AmpScaler / GradScaler);
static side contrib/mixed_precision/decorator.py:36.

TPU-first: bf16 is the native mixed-precision dtype — no loss scaling needed
(bf16 has fp32's exponent range), so O1/O2 map to bf16 compute and
GradScaler degenerates to a pass-through unless fp16 is forced.  The
white/black list machinery survives as the op-level autocast policy consulted
by Primitive dispatch (framework/core.py amp_state).
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..framework import core
from ..framework.tensor import Tensor

# fp16_lists.py parity, keyed by REGISTERED primitive names (the paddle op
# names used at Primitive() registration): MXU ops whiten, numerically
# sensitive ops blacken
WHITE_LIST = {"matmul_v2", "mul", "conv2d", "conv2d_nobias",
              "conv2d_transpose", "conv2d_transpose_nobias", "einsum",
              "scaled_dot_product_attention",
              "scaled_dot_product_attention_mask",
              "flash_attention", "flash_attention_bias", "bilinear_nobias"}
BLACK_LIST = {"exp", "log", "softmax", "log_softmax",
              "softmax_with_cross_entropy", "softmax_with_cross_entropy_soft",
              "layer_norm", "layer_norm_nogb", "batch_norm_train",
              "batch_norm_eval", "reduce_sum", "reduce_mean", "cumsum",
              "elementwise_pow", "p_norm", "frobenius_norm", "bce_loss",
              "kldiv_loss", "log_loss"}
# int8 inference sites (ops/int8.py): autocast must neither down-cast the
# fp32 scale/bias epilogue operands nor up-cast the int8 tensors — the
# integer dot IS the precision contract.  Exempt even under O2.
AMP_EXEMPT = {"linear_int8", "conv2d_int8", "matmul_int8"}


class AmpState:
    def __init__(self, enable=True, dtype="bfloat16", custom_white_list=None,
                 custom_black_list=None, level="O1"):
        self.enable = enable
        self.dtype = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") \
            else jnp.float16
        self.level = level
        self.white = (WHITE_LIST | set(custom_white_list or ())) - \
            set(custom_black_list or ())
        self.black = (BLACK_LIST | set(custom_black_list or ())) - \
            set(custom_white_list or ())

    def cast_policy(self, op_name):
        """'low' -> cast fp32 inputs to amp dtype; 'high' -> cast to fp32;
        None -> leave as-is. O2 casts everything but the black list."""
        if not self.enable:
            return None
        if op_name in AMP_EXEMPT:
            return None
        if op_name in self.black:
            return "high"
        if self.level == "O2" or op_name in self.white:
            return "low"
        return None


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast (dygraph amp_guard :91 parity)."""
    state = AmpState(enable, dtype, custom_white_list, custom_black_list,
                     level)
    with core.amp_guard_state(state if enable else None):
        yield


amp_guard = auto_cast


class GradScaler:
    """loss_scaler.py:27 parity.

    With bf16 (TPU default) scaling is mathematically unnecessary: scale()
    and step()/update() pass through at scale 1.  The dynamic-scale state
    machine (incr_every_n_steps / decr on nan) is kept for fp16 use and API
    compatibility (check_finite mirrors check_finite_and_unscale_op,
    operators/amp/).
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable or self._scale == 1.0:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import jax.numpy as jnp
        inv = 1.0 / self._scale
        found = False
        for p in (optimizer._parameters or []):
            if p.grad is not None:
                g = p.grad._value * inv
                if not bool(jnp.all(jnp.isfinite(g))):
                    found = True
                p.grad._value = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update_scale()

    def minimize(self, optimizer, scaled_loss):
        # Reference contract (loss_scaler.py docstring): the caller runs
        # scaled.backward() first, then minimize().  Only trigger backward
        # here if it hasn't run on THIS loss yet (graph live, no prior
        # backward) — a retain_graph backward must not be re-run, which
        # would double every grad; a fresh un-backwarded loss still works
        # even when grads from earlier micro-batches are being accumulated.
        if scaled_loss._node is not None and not scaled_loss._bwd_done:
            scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        pass  # folded into step()

    def on_step_result(self, found_inf: bool):
        """Drive the dynamic-scale state machine from OUTSIDE the eager
        step()/unscale_() path — the compiled TrainStep's in-graph
        numerics sentinel reports each step's verdict here, so a skipped
        (non-finite) step backs the scale off exactly like the reference's
        update_loss_scaling op, and a good-step streak grows it."""
        self._found_inf = bool(found_inf)
        self._update_scale()

    def _update_scale(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state["good_steps"]
        self._bad_steps = state["bad_steps"]


AmpScaler = GradScaler


def decorate(models=None, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity (contrib/mixed_precision/decorator.py:36).

    O2 on TPU: cast model params to bf16 for storage/compute; the optimizer
    keeps true fp32 master weights (Optimizer._trees seeds an ``@master``
    accumulator the first time it sees a low-precision param, updates the
    master in fp32, and casts back to the stored dtype) — matching the
    reference multi_precision path, so sub-ulp updates are not lost.
    ``master_weight=False`` opts out."""
    if level == "O2" and models is not None:
        targets = models if isinstance(models, (list, tuple)) else [models]
        for m in targets:
            for p in m.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._value = p._value.astype(
                        jnp.bfloat16 if dtype in ("bfloat16", "bf16")
                        else jnp.float16)
    if optimizers is not None:
        opts = optimizers if isinstance(optimizers, (list, tuple)) \
            else [optimizers]
        for o in opts:
            o._use_master_weights = master_weight
    if optimizers is None:
        return models
    return models, optimizers
