"""Step-level atomic checkpointing: sharded, rank-aware, async, GC'd.

Reference parity: incubate/checkpoint's auto-checkpoint + the
checkpoint-notify the PS trainers use — upgraded from epoch-granularity
whole-file writes to the layout a long multi-host TPU run needs:

``<root>/step_00000042/``
    ``MANIFEST.json``              — committed LAST; the atomicity point
    ``params.rank00000.pdparams``  — one file per (payload name, rank)
    ``opt.rank00000.pdparams``
    ``commit.rank00001.json``      — non-zero ranks' commit markers

A checkpoint is visible if and only if its manifest exists and validates:
every payload file is written via temp+fsync+``os.replace``
(checkpoint.atomic), each with a sha256 recorded in the manifest, and the
manifest itself is the final atomic write — so an interrupted save never
yields a loadable-but-corrupt checkpoint, it yields an incomplete dir the
next GC sweeps.

Rank protocol: every rank writes its own shard files; non-zero ranks then
commit a marker listing (file, sha256, size); rank 0 polls for all
markers and writes the merged manifest.  Single-process jobs degenerate
to "write files, write manifest".

Async saves run on one background thread with backpressure (a second
save waits for the first): state is snapshotted to host numpy BEFORE
``save`` returns, because the donated train-step buffers the payload
references are invalidated by the very next step.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .atomic import (CheckpointCorruptError, atomic_write_bytes,
                     atomic_pickle_save, sha256_file, verified_pickle_load)

_MANIFEST = "MANIFEST.json"
_MANIFEST_FORMAT = "paddle_tpu.checkpoint.manifest.v1"
_STEP_RE = re.compile(r"^step_(\d{8})$")


def _step_dirname(step: int) -> str:
    return f"step_{step:08d}"


def _payload_filename(name: str, rank: int) -> str:
    return f"{name}.rank{rank:05d}.pdparams"


def _commit_marker(rank: int) -> str:
    return f"commit.rank{rank:05d}.json"


def _host_snapshot(obj: Any) -> Any:
    """Pull every array leaf to host numpy NOW — async writers must not
    hold references into donated device buffers."""
    if isinstance(obj, dict):
        return {k: _host_snapshot(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_host_snapshot(v) for v in obj)
    if hasattr(obj, "numpy"):           # framework Tensor
        return np.asarray(obj.numpy())
    if hasattr(obj, "dtype") and hasattr(obj, "shape") and \
            not isinstance(obj, np.ndarray):
        return np.asarray(obj)          # jax.Array and friends
    return obj


def read_manifest(step_dir: str) -> Optional[dict]:
    """The manifest, or None when absent/unparseable (incomplete save)."""
    try:
        with open(os.path.join(step_dir, _MANIFEST)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if m.get("format") != _MANIFEST_FORMAT:
        return None
    return m


def is_complete(step_dir: str, verify: bool = False) -> bool:
    """Complete = manifest present + every listed file present at its
    recorded size (+ checksum match when ``verify``)."""
    m = read_manifest(step_dir)
    if m is None:
        return False
    for fname, meta in m.get("files", {}).items():
        path = os.path.join(step_dir, fname)
        try:
            if os.path.getsize(path) != meta["size"]:
                return False
        except OSError:
            return False
        if verify and sha256_file(path) != meta["sha256"]:
            return False
    return True


def complete_steps(root: str, verify: bool = False) -> List[int]:
    """Ascending list of step numbers with complete checkpoints."""
    try:
        entries = os.listdir(root)
    except OSError:
        return []
    out = []
    for e in entries:
        mt = _STEP_RE.match(e)
        if mt and is_complete(os.path.join(root, e), verify=verify):
            out.append(int(mt.group(1)))
    return sorted(out)


def latest_complete_step(root: str, verify: bool = False) -> Optional[int]:
    steps = complete_steps(root, verify=verify)
    return steps[-1] if steps else None


class CheckpointManager:
    """Owns one checkpoint root: atomic saves, verified loads, retention.

    Parameters
    ----------
    root: checkpoint directory (created on first save).
    keep: retain this many newest complete checkpoints (0/None =
        unlimited; default from ``FLAGS_ckpt_keep``).
    rank / world_size: shard identity; default from the launcher env
        (``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM``).
    async_save: write on a background thread (one in flight; a second
        save applies backpressure by waiting for the first).
    commit_timeout: how long rank 0 waits for other ranks' commit
        markers before declaring the save failed.
    """

    def __init__(self, root: str, keep: Optional[int] = None,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 async_save: bool = False, commit_timeout: float = 120.0):
        from ..framework import flags as _flags
        self.root = str(root)
        self.keep = _flags.flag("ckpt_keep") if keep is None else int(keep)
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0")) \
            if rank is None else int(rank)
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) \
            if world_size is None else int(world_size)
        self.async_save = bool(async_save)
        self.commit_timeout = float(commit_timeout)
        self._inflight: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()

    # -- save ---------------------------------------------------------------
    def save(self, step: int, payload: Dict[str, Any],
             wait: Optional[bool] = None) -> str:
        """Checkpoint ``payload`` (a ``{name: state}`` dict) at ``step``.

        Returns the step directory path.  With ``async_save`` the write
        happens off-thread and this returns as soon as the host snapshot
        is taken; pass ``wait=True`` (or call :meth:`wait`) to block until
        the manifest is committed.
        """
        if not isinstance(payload, dict) or not payload:
            raise ValueError("payload must be a non-empty {name: state} dict")
        bad = [n for n in payload
               if "/" in n or n.startswith("commit.") or n == _MANIFEST]
        if bad:
            raise ValueError(f"illegal payload names: {bad}")
        self._raise_pending()
        snapshot = _host_snapshot(payload)
        step_dir = os.path.join(self.root, _step_dirname(int(step)))
        if self.async_save and not wait:
            self.wait()                 # backpressure: one in flight
            t = threading.Thread(target=self._save_worker,
                                 args=(int(step), step_dir, snapshot),
                                 daemon=True)
            with self._lock:
                self._inflight = t
            t.start()
        else:
            self._save_worker(int(step), step_dir, snapshot)
            self._raise_pending()
        return step_dir

    def _save_worker(self, step: int, step_dir: str, snapshot: dict):
        try:
            t0 = time.perf_counter()
            os.makedirs(step_dir, exist_ok=True)
            files = {}
            for name, obj in snapshot.items():
                fname = _payload_filename(name, self.rank)
                digest, size = atomic_pickle_save(
                    obj, os.path.join(step_dir, fname))
                files[fname] = {"sha256": digest, "size": size,
                                "rank": self.rank, "payload": name}
            if self.rank != 0:
                marker = json.dumps({"rank": self.rank, "files": files})
                atomic_write_bytes(
                    os.path.join(step_dir, _commit_marker(self.rank)),
                    marker.encode())
                return
            files.update(self._collect_commit_markers(step_dir))
            manifest = {"format": _MANIFEST_FORMAT, "step": step,
                        "world_size": self.world_size, "files": files,
                        "wall": time.time()}
            # the commit point: the checkpoint exists from here on
            atomic_write_bytes(os.path.join(step_dir, _MANIFEST),
                               json.dumps(manifest, indent=1).encode())
            from ..utils.monitor import stat_add
            stat_add("ckpt_save_count")
            stat_add("ckpt_save_ms_total",
                     int(round((time.perf_counter() - t0) * 1e3)))
            self.gc()
        except BaseException as e:  # surfaced on the next save/wait
            with self._lock:
                self._error = e
        finally:
            with self._lock:
                if self._inflight is threading.current_thread():
                    self._inflight = None

    def _collect_commit_markers(self, step_dir: str) -> dict:
        """Rank 0: wait for every non-zero rank's commit marker."""
        merged = {}
        pending = set(range(1, self.world_size))
        # monotonic deadline: a wall-clock jump must not spuriously time
        # out (or extend) a commit wait
        deadline = time.monotonic() + self.commit_timeout
        while pending:
            for r in sorted(pending):
                path = os.path.join(step_dir, _commit_marker(r))
                try:
                    with open(path) as f:
                        merged.update(json.load(f)["files"])
                    pending.discard(r)
                except (OSError, ValueError):
                    continue
            if not pending:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"checkpoint commit: ranks {sorted(pending)} never "
                    f"committed under {step_dir} "
                    f"(timeout {self.commit_timeout}s)")
            time.sleep(0.05)
        return merged

    def wait(self):
        """Block until any in-flight async save commits; re-raise its
        error here if it failed."""
        with self._lock:
            t = self._inflight
        if t is not None:
            t.join()
        self._raise_pending()

    def _raise_pending(self):
        with self._lock:
            e, self._error = self._error, None
        if e is not None:
            raise e

    # -- load ---------------------------------------------------------------
    def load(self, step: Optional[int] = None, verify: bool = True,
             return_numpy: bool = False) -> Tuple[int, Dict[str, Any]]:
        """Load this rank's shard of checkpoint ``step`` (default: newest
        complete).  Corrupt candidates are skipped — the loader falls back
        to the previous complete step, matching the crash model (a torn
        newest checkpoint must not take the job down).

        Returns ``(step, {name: state})``; raises FileNotFoundError when
        no complete checkpoint survives.
        """
        candidates = ([int(step)] if step is not None
                      else list(reversed(complete_steps(self.root))))
        last_err = None
        for s in candidates:
            step_dir = os.path.join(self.root, _step_dirname(s))
            m = read_manifest(step_dir)
            if m is None:
                last_err = FileNotFoundError(
                    f"no manifest under {step_dir}")
                continue
            try:
                out = {}
                for fname, meta in m["files"].items():
                    if meta.get("rank", 0) != self.rank:
                        continue
                    out[meta.get("payload", fname)] = verified_pickle_load(
                        os.path.join(step_dir, fname),
                        expect_sha256=meta["sha256"] if verify else None,
                        return_numpy=return_numpy)
                return s, out
            except (OSError, CheckpointCorruptError) as e:
                last_err = e
                continue
        raise FileNotFoundError(
            f"no complete checkpoint under {self.root}"
            + (f" (last error: {last_err})" if last_err else ""))

    def latest_step(self) -> Optional[int]:
        return latest_complete_step(self.root)

    def complete_steps(self) -> List[int]:
        return complete_steps(self.root)

    # -- retention ----------------------------------------------------------
    def gc(self):
        """Drop old checkpoints: keep the ``keep`` newest complete steps;
        incomplete dirs OLDER than the newest complete step are crashed
        saves and go too.  Incomplete dirs newer than it may be another
        rank's in-flight save and are left alone."""
        if self.rank != 0:
            return
        import shutil
        steps = complete_steps(self.root)
        if not steps:
            return
        newest = steps[-1]
        doomed = steps[:-self.keep] if self.keep and self.keep > 0 else []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return
        from ..utils.monitor import stat_add
        for e in entries:
            mt = _STEP_RE.match(e)
            if not mt:
                continue
            s = int(mt.group(1))
            path = os.path.join(self.root, e)
            if s in doomed or (s < newest and not is_complete(path)):
                shutil.rmtree(path, ignore_errors=True)
                stat_add("ckpt_gc_count")
