"""paddle_tpu.checkpoint: step-level atomic, sharded checkpointing.

The durability contract lives in :mod:`.atomic` (temp+fsync+``os.replace``
writes with sha256 verification); :mod:`.manager` builds the step-dir
layout, the commit-last manifest, async saves and retention on top of it.
``incubate.checkpoint.auto_checkpoint`` and the TrainStep/hapi hooks are
thin consumers of this subsystem.
"""
from .atomic import (  # noqa: F401
    CheckpointCorruptError, atomic_pickle_save, atomic_write_bytes,
    sha256_file, verified_pickle_load)
from .manager import (  # noqa: F401
    CheckpointManager, complete_steps, is_complete, latest_complete_step,
    read_manifest)

__all__ = [
    "CheckpointManager", "CheckpointCorruptError", "atomic_write_bytes",
    "atomic_pickle_save", "verified_pickle_load", "sha256_file",
    "complete_steps", "is_complete", "latest_complete_step",
    "read_manifest",
]
