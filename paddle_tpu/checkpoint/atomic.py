"""Atomic, checksummed file writes — the durability primitives every
checkpoint path in the framework routes through.

Reference parity: the reference's checkpoint-notify machinery
(incubate/checkpoint/checkpoint_saver.py) relies on HDFS rename atomicity;
on a posix/local filesystem the equivalent contract is

    write temp (same dir) -> flush -> fsync(file) -> os.replace -> fsync(dir)

so a crash at ANY point leaves either the old file or the new file, never
a torn hybrid.  The directory fsync makes the rename itself durable (a
power cut after replace but before the dirent hits disk would otherwise
resurrect the old file).

Every payload additionally carries a sha256 so the LOADER can tell a
complete file from a corrupt one — rename atomicity protects against
crashes mid-write, checksums protect against everything else (partial
scp, bit rot, a writer that died before the replace but whose temp file
was mistaken for real data).
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from typing import Any, Tuple


def fsync_dir(path: str) -> None:
    """Durably commit a directory's entries (rename targets)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms/filesystems without dir-open support
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def atomic_write_bytes(path: str, data: bytes, durable: bool = True) -> str:
    """Write ``data`` to ``path`` atomically; returns the sha256 hexdigest.

    The temp file lives in the SAME directory as the target — os.replace
    is only atomic within a filesystem, and a same-dir temp also means GC
    of debris is local to the checkpoint dir.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.",
                               dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            if durable:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if durable:
        fsync_dir(d)
    return hashlib.sha256(data).hexdigest()


def atomic_pickle_save(obj: Any, path: str, protocol: int = 4,
                       durable: bool = True) -> Tuple[str, int]:
    """Serialize ``obj`` in the framework checkpoint format (the same
    magic-tagged pickle ``framework.io_state.save`` emits, so either
    loader reads either writer) and commit it atomically.

    Returns (sha256, byte size).
    """
    from ..framework.io_state import _MAGIC, _to_saveable
    payload = pickle.dumps({"magic": _MAGIC, "obj": _to_saveable(obj)},
                           protocol=protocol)
    return atomic_write_bytes(path, payload, durable=durable), len(payload)


def verified_pickle_load(path: str, expect_sha256: str = None,
                         return_numpy: bool = False) -> Any:
    """Load a checkpoint payload, optionally verifying its checksum first.

    Raises ``CheckpointCorruptError`` on mismatch so callers can
    distinguish "corrupt file" (fall back to an older checkpoint) from
    genuine IO errors.
    """
    if expect_sha256 is not None:
        actual = sha256_file(path)
        if actual != expect_sha256:
            raise CheckpointCorruptError(
                f"checksum mismatch for {path}: "
                f"expected {expect_sha256[:12]}…, got {actual[:12]}…")
    from ..framework.io_state import load
    return load(path, return_numpy=return_numpy)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file exists but fails verification (torn write, bit
    rot, truncation).  Loaders treat this as "checkpoint absent" and fall
    back to the previous complete step."""
