"""Diagnostic records for the graph-lint pass suite.

Reference parity: paddle/fluid/framework/ir/pass.h turns every graph pass
into graph-in/graph-out with AnalysisPass diagnostics surfaced through glog;
here every finding is a structured :class:`Diagnostic` carrying the pass id,
severity, human message and — crucially — *user-level source provenance*
(jax ``source_info`` → ``file:line``) so a warning printed at trace time
points at the model code that caused it, not at framework internals.
"""
from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class Severity(enum.IntEnum):
    """Per-pass severity ladder (pass.h's error/warning split)."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):  # "warning", not "Severity.WARNING", in reports
        return self.name.lower()


class GraphLintWarning(UserWarning):
    """Category for warn-mode findings (filterable via warnings.filter)."""


@dataclass
class Diagnostic:
    """One finding from one pass over one traced program."""

    pass_id: str
    severity: Severity
    message: str
    site: str = ""                 # compile-cache site, e.g. "jit:forward"
    location: Optional[str] = None  # user "file.py:123 (fn)" when known
    kind: str = ""                 # jit | executor | train_step | cli | ast
    extra: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        return {"pass": self.pass_id, "severity": str(self.severity),
                "message": self.message, "site": self.site,
                "location": self.location, "kind": self.kind,
                **({"extra": self.extra} if self.extra else {})}

    def __str__(self):
        loc = f"{self.location}: " if self.location else ""
        return (f"[{self.pass_id}] {str(self.severity).upper()} {loc}"
                f"{self.message}" + (f" (at {self.site})" if self.site
                                     else ""))


class LintReport:
    """All findings from one PassManager.run over one traced program."""

    def __init__(self, site: str = "", kind: str = ""):
        self.site = site
        self.kind = kind
        self.diagnostics: List[Diagnostic] = []

    def extend(self, diags):
        self.diagnostics.extend(diags)

    def __len__(self):
        return len(self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def __bool__(self):
        return bool(self.diagnostics)

    def by_severity(self, severity: Severity) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def n_errors(self) -> int:
        return len(self.by_severity(Severity.ERROR))

    @property
    def n_warnings(self) -> int:
        return len(self.by_severity(Severity.WARNING))

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for d in self.diagnostics:
            out[d.pass_id] = out.get(d.pass_id, 0) + 1
        return out

    def format(self) -> str:
        head = f"graph-lint: {len(self.diagnostics)} finding(s)" + \
            (f" at {self.site}" if self.site else "")
        if not self.diagnostics:
            return head.replace("finding(s)", "findings — clean")
        lines = [head]
        for d in sorted(self.diagnostics, key=lambda d: -d.severity):
            lines.append("  " + str(d))
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Any]:
        return {"site": self.site, "kind": self.kind,
                "counts": self.counts(),
                "n_errors": self.n_errors, "n_warnings": self.n_warnings,
                "diagnostics": [d.as_dict() for d in self.diagnostics]}

    def to_json(self, **kw) -> str:
        return json.dumps(self.as_dict(), **kw)
