"""paddle_tpu.analysis.protocol — static verification of the cluster
protocols.

Third tier of the analysis stack (after the jaxpr pass suite and the
HLO admission audit): the distributed serving plane's protocols are
declared as data (:mod:`.spec`, registered next to the implementing
code in ``serving/cluster/`` and ``serving/sessions.py``) and verified
by exhaustive explicit-state exploration (:mod:`.model_check`,
:mod:`.models`) under the same injected faults the chaos drills sample.
:mod:`.mutations` is the seeded-bug corpus that keeps the checker
honest; ``tools/proto_check.py`` is the CLI/CI face.

Pure Python, no JAX, no devices — importable anywhere.
"""
from __future__ import annotations

from .spec import (Invariant, ProtocolSpec, SpecError,  # noqa: F401
                   Transition, get_protocol, load_builtin_specs,
                   register_protocol, registered_protocols)
from .model_check import (Action, CheckResult, ProtocolModel,  # noqa: F401
                          Violation, check_model)
from .models import ALL_MODELS, build_model  # noqa: F401
from . import mutations  # noqa: F401

__all__ = [
    "ProtocolSpec", "Transition", "Invariant", "SpecError",
    "register_protocol", "registered_protocols", "get_protocol",
    "load_builtin_specs", "ProtocolModel", "CheckResult", "Violation",
    "check_model", "ALL_MODELS", "build_model", "mutations",
    "check_all",
]


def check_all(mutations=frozenset(), max_states: int = 500_000):
    """Model-check every protocol (after loading the specs registered
    in the serving modules).  Returns {protocol: CheckResult}."""
    load_builtin_specs()
    muts = frozenset(mutations)
    return {name: check_model(build_model(name, mutations=muts),
                              max_states=max_states)
            for name in sorted(ALL_MODELS)}
