"""Seeded-bug corpus: mutation-style validation of the protocol checker.

A model checker that never fires is indistinguishable from one that
cannot fire.  This corpus seeds ~10 realistic protocol bugs — each one
a single dropped write, skipped gate, or reordered step of the kind a
refactor could plausibly introduce — and the validation contract
(tools/proto_check.py --mutations, tests/test_protocol_check.py) is:

  * the UNMUTATED models check clean (zero false positives), and
  * every mutation drives at least one declared invariant (or spec
    conformance) to a violating state (zero false negatives).

Protocol mutations are flags the world models in :mod:`.models`
interpret; concurrency-lint mutations are source transforms applied to
real serving code (drop a ``with self._lock:`` guard) or to a
representative two-lock module (swap a nested acquisition pair), which
:mod:`..concurrency_lint` must flag.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

__all__ = ["ProtocolMutation", "LintMutation", "PROTOCOL_MUTATIONS",
           "LINT_MUTATIONS", "all_mutation_ids"]


@dataclass(frozen=True)
class ProtocolMutation:
    """One seeded protocol bug: a flag the world model interprets."""

    mutation_id: str
    model: str            # key into models.ALL_MODELS
    doc: str
    expect: Tuple[str, ...]   # invariant name(s) that may catch it


PROTOCOL_MUTATIONS: Dict[str, ProtocolMutation] = {m.mutation_id: m for m in [
    ProtocolMutation(
        "lifecycle.drop_tombstone_write", "replica-lifecycle",
        "clean retirement skips the __serving_replica/retired/<id> "
        "tombstone store write — a router discovering the rendezvous "
        "prefix later resurrects the dead registration as a ghost, which "
        "then heartbeat-evicts a replica that was ALSO cleanly "
        "deregistered",
        ("tombstone-evict-exclusive", "dispatch-targets-live")),
    ProtocolMutation(
        "lifecycle.accept_while_draining", "replica-lifecycle",
        "the drain order does not flip the server to stop-accepting — "
        "new work keeps landing through draining/drained and the replica "
        "retires with a request still in flight (the request dies with "
        "the process exit)",
        ("no-retire-with-inflight", "dispatch-targets-live")),
    ProtocolMutation(
        "lifecycle.retire_undrained", "replica-lifecycle",
        "the controller retires (tombstone + deregister) off the drain "
        "ORDER instead of the drain REPORT — admitted work is still in "
        "flight when the process exit is scheduled",
        ("no-retire-with-inflight",)),
    ProtocolMutation(
        "sessions.skip_park_on_drain", "session",
        "drain tears down decode slots without parking active rows into "
        "the session store — a clean drain silently loses the "
        "conversation (zero owners with no SIGKILL excuse)",
        ("one-owner",)),
    ProtocolMutation(
        "sessions.export_copies", "session",
        "export_bytes serializes WITHOUT removing (copy semantics) — "
        "after the import both replicas own the session and the stale "
        "copy can clobber the live one's next park",
        ("one-owner",)),
    ProtocolMutation(
        "sessions.import_ignores_newer", "session",
        "import_bytes drops the t_park keep-newer check — a replayed "
        "migration blob overwrites a fresher turn's parked snapshot",
        ("no-stale-clobber",)),
    ProtocolMutation(
        "rollout.commit_before_apply", "rolling-update",
        "the rollout journal commits the replacement step BEFORE "
        "spawn+retire are applied — a crash between commit and apply "
        "resumes past the step, leaving an old-version replica serving "
        "while the journal claims it replaced",
        ("journal-implies-applied",)),
    ProtocolMutation(
        "rollout.skip_canary_gate", "rolling-update",
        "promotion skips the canary logits bit-match gate — a "
        "mismatched new version enters rotation",
        ("no-mismatched-promotion",)),
    ProtocolMutation(
        "rollout.drain_before_spawn", "rolling-update",
        "the replacement loop retires the old replica before its "
        "replacement is spawned — capacity pays for the update and a "
        "spawn failure strands the fleet a replica short",
        ("spawn-before-drain",)),
    ProtocolMutation(
        "handoff.skip_integrity_check", "kv-handoff",
        "decode_from skips the magic/header integrity check and decodes "
        "whatever bytes arrive — a torn wire blob becomes silently "
        "corrupt KV planes instead of a retryable rejection",
        ("no-torn-decode",)),
    ProtocolMutation(
        "handoff.retry_after_reply", "kv-handoff",
        "the router's retry loop re-dispatches a decode after the reply "
        "already left (timeout misclassified as retryable) — the client "
        "can observe two replies for one request",
        ("reply-at-most-once",)),
]}


@dataclass(frozen=True)
class LintMutation:
    """One seeded concurrency bug: a source transform the lint must
    flag.  ``apply(source) -> mutated_source`` returns None when the
    anchor text is missing (the corpus test then fails loudly rather
    than silently passing)."""

    mutation_id: str
    doc: str
    target: str                # repo-relative path or "<corpus>"
    expect_pass: str           # lint pass id that must fire
    apply: Callable[[str], Optional[str]]


def _drop_guard(source: str) -> Optional[str]:
    """Neutralize the first ``with self._lock:`` in SessionStore.put —
    the guarded _ram/_ram_bytes writes become lock-free."""
    anchor = "with self._lock:\n            sid = snap.session_id"
    if anchor not in source:
        return None
    return source.replace(
        anchor, "if True:\n            sid = snap.session_id", 1)


# a representative two-lock module in the router/store idiom: every
# cross-structure path takes _route_lock before _table_lock
_ORDER_CORPUS = '''\
import threading


class Router:
    def __init__(self):
        self._route_lock = threading.Lock()
        self._table_lock = threading.Lock()
        self._routes = {}      # guarded-by: _route_lock
        self._table = {}       # guarded-by: _table_lock

    def add(self, key, val):
        with self._route_lock:
            self._routes[key] = val
            with self._table_lock:
                self._table[key] = val

    def drop(self, key):
        with self._route_lock:
            self._routes.pop(key, None)
            with self._table_lock:
                self._table.pop(key, None)
'''


def _swap_lock_pair(source: str) -> Optional[str]:
    """Reverse the nested acquisition order in ``drop`` — the classic
    AB/BA deadlock when ``add`` and ``drop`` race."""
    anchor = ("        with self._route_lock:\n"
              "            self._routes.pop(key, None)\n"
              "            with self._table_lock:\n"
              "                self._table.pop(key, None)\n")
    if anchor not in source:
        return None
    return source.replace(anchor, (
        "        with self._table_lock:\n"
        "            with self._route_lock:\n"
        "                self._routes.pop(key, None)\n"
        "                self._table.pop(key, None)\n"), 1)


LINT_MUTATIONS: Dict[str, LintMutation] = {m.mutation_id: m for m in [
    LintMutation(
        "lint.drop_guard",
        "remove the lock acquisition around SessionStore.put's _ram "
        "bookkeeping — every guarded-by:_lock field write inside "
        "becomes unguarded",
        "paddle_tpu/serving/sessions.py",
        "guarded-field", _drop_guard),
    LintMutation(
        "lint.swap_lock_pair",
        "reverse one nested lock acquisition in a two-lock module — the "
        "acquisition-order graph gains an AB/BA cycle (deadlock hazard)",
        "<corpus>", "lock-order-cycle", _swap_lock_pair),
]}

ORDER_CORPUS_SOURCE = _ORDER_CORPUS


def all_mutation_ids() -> Tuple[str, ...]:
    return tuple(sorted(PROTOCOL_MUTATIONS)) + tuple(sorted(LINT_MUTATIONS))
