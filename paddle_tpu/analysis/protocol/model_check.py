"""Explicit-state model checker for the cluster protocols.

The chaos drills (tests/test_lifecycle.py) sample a handful of
interleavings out of an exponential space; this checker enumerates ALL
of them over small finite world models: breadth-first search from the
initial state, expanding every enabled action (router, N replicas,
controller, and injected faults — SIGKILL, drain-hang, store-write
loss — are just more actions), memoizing visited states, and evaluating
every declared invariant in every reachable state.  A violation comes
back with the full action trace from the initial state (parent-pointer
reconstruction), so a protocol bug reads like a drill transcript.

Conformance: each world-model action is tagged with the
:class:`~.spec.ProtocolSpec` transitions it claims to implement; a step
the registered spec does not allow is reported as a conformance error.
The checker also reports per-spec transition coverage, so a declared
edge no model exercises is visible.

Everything here is plain Python over hashable tuples — no JAX, no
devices; the full four-protocol sweep runs in seconds on one CPU core
(the acceptance bar is < 30 s; see tools/proto_check.py --json for the
measured state counts).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .spec import ProtocolSpec, get_protocol

__all__ = ["Action", "ProtocolModel", "Violation", "CheckResult",
           "check_model"]

# one world-model step: a display label, the spec transitions it
# implements (tuples of (spec_name, src, action, dst)), and the
# successor state
Action = Tuple[str, Tuple[Tuple[str, str, str, str], ...], Any]


class ProtocolModel:
    """Base class for a finite world model of one protocol.

    Subclasses define ``name``, ``spec_names`` (registered specs this
    model conforms to), ``initial_state()`` (a hashable value),
    ``actions(state)`` (iterable of :data:`Action`) and ``invariants``
    (tuples of (name, doc, predicate(state) -> bool)).
    """

    name: str = "model"
    spec_names: Tuple[str, ...] = ()
    invariants: Tuple[Tuple[str, str, Callable[[Any], bool]], ...] = ()

    def initial_state(self) -> Any:
        raise NotImplementedError

    def actions(self, state: Any) -> Iterable[Action]:
        raise NotImplementedError


@dataclass
class Violation:
    """One invariant violation (or conformance error) with its trace."""

    invariant: str
    doc: str
    state: Any
    trace: Tuple[str, ...]
    kind: str = "invariant"   # "invariant" | "conformance"

    def as_dict(self) -> dict:
        return {"invariant": self.invariant, "doc": self.doc,
                "kind": self.kind, "depth": len(self.trace),
                "trace": list(self.trace), "state": repr(self.state)}

    def __str__(self) -> str:
        steps = "\n".join(f"    {i + 1}. {a}"
                          for i, a in enumerate(self.trace)) or "    (initial)"
        return (f"[{self.kind}] {self.invariant}: {self.doc}\n"
                f"  state: {self.state!r}\n  trace ({len(self.trace)} "
                f"steps):\n{steps}")


@dataclass
class CheckResult:
    """Outcome of exhausting one model's state space."""

    protocol: str
    states: int = 0
    transitions: int = 0
    max_depth: int = 0
    elapsed_s: float = 0.0
    complete: bool = True
    violations: List[Violation] = field(default_factory=list)
    invariants_checked: Tuple[str, ...] = ()
    spec_coverage: Dict[str, dict] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.complete and not self.violations

    def as_dict(self) -> dict:
        return {
            "protocol": self.protocol, "ok": self.ok,
            "states": self.states, "transitions": self.transitions,
            "max_depth": self.max_depth,
            "elapsed_s": round(self.elapsed_s, 3),
            "complete": self.complete,
            "invariants_checked": list(self.invariants_checked),
            "violations": [v.as_dict() for v in self.violations],
            "spec_coverage": self.spec_coverage,
        }

    def format(self) -> str:
        head = (f"protocol {self.protocol}: {self.states} states, "
                f"{self.transitions} transitions, depth {self.max_depth}, "
                f"{self.elapsed_s:.2f}s — "
                f"{'OK' if self.ok else 'VIOLATIONS'}")
        if not self.violations:
            return head
        return head + "\n" + "\n".join(str(v) for v in self.violations)


def _trace_of(parents: Dict[Any, Tuple[Any, str]], state: Any) -> Tuple[str, ...]:
    steps: List[str] = []
    cur = state
    while True:
        entry = parents.get(cur)
        if entry is None:
            break
        cur, label = entry
        steps.append(label)
    return tuple(reversed(steps))


def check_model(model: ProtocolModel, max_states: int = 500_000,
                check_conformance: bool = True) -> CheckResult:
    """Exhaust ``model``'s reachable state space (BFS) and check every
    invariant in every state.

    Violating states are recorded (first witness per invariant, with the
    shortest trace — BFS order guarantees minimality) and NOT expanded
    further, so a mutant model's blow-up stays bounded.  ``max_states``
    is a safety net: hitting it marks the result incomplete.
    """
    t0 = time.monotonic()
    specs: Dict[str, ProtocolSpec] = {}
    if check_conformance:
        specs = {n: get_protocol(n) for n in model.spec_names}
    exercised: Dict[str, set] = {n: set() for n in specs}
    conf_seen: set = set()

    result = CheckResult(
        protocol=model.name,
        invariants_checked=tuple(n for n, _, _ in model.invariants))
    init = model.initial_state()
    parents: Dict[Any, Tuple[Any, str]] = {}
    depth: Dict[Any, int] = {init: 0}
    violated: Dict[str, Violation] = {}

    def _check(state) -> bool:
        """Evaluate invariants; record first witness; True = clean."""
        clean = True
        for name, doc, pred in model.invariants:
            if not pred(state):
                clean = False
                if name not in violated:
                    violated[name] = Violation(
                        invariant=name, doc=doc, state=state,
                        trace=_trace_of(parents, state))
        return clean

    frontier = deque([init])
    result.states = 1
    expand = _check(init)
    if not expand:
        frontier.clear()
    while frontier:
        state = frontier.popleft()
        d = depth[state]
        for label, spec_steps, nxt in model.actions(state):
            result.transitions += 1
            for step in spec_steps:
                spec_name, src, action, dst = step
                spec = specs.get(spec_name)
                if spec is None:
                    continue
                exercised[spec_name].add((src, action, dst))
                if not spec.allows(src, action, dst) \
                        and step not in conf_seen:
                    conf_seen.add(step)
                    violated.setdefault(
                        f"conformance:{spec_name}:{action}",
                        Violation(
                            invariant=f"spec-conformance:{spec_name}",
                            doc=f"model step {src} --{action}--> {dst} "
                                f"is not a declared transition of "
                                f"protocol {spec_name!r}",
                            state=nxt,
                            trace=_trace_of(parents, state) + (label,),
                            kind="conformance"))
            if nxt in depth:
                continue
            depth[nxt] = d + 1
            parents[nxt] = (state, label)
            result.states += 1
            result.max_depth = max(result.max_depth, d + 1)
            if result.states >= max_states:
                result.complete = False
                frontier.clear()
                break
            if _check(nxt):
                frontier.append(nxt)
    result.violations = sorted(violated.values(),
                               key=lambda v: (v.kind, v.invariant))
    for name, spec in specs.items():
        declared = {(t.src, t.action, t.dst) for t in spec.transitions}
        used = exercised[name] & declared
        result.spec_coverage[name] = {
            "declared": len(declared), "exercised": len(used),
            "unexercised": sorted(
                f"{s} --{a}--> {d}" for (s, a, d) in declared - used),
        }
    result.elapsed_s = time.monotonic() - t0
    return result
