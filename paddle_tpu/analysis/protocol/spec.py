"""Declarative protocol state-machine specs, registered beside the code.

The serving cluster's distributed protocols (replica lifecycle, session
park/migrate/restore, rolling update, KV handoff) are documented today
as prose + chaos drills.  This module gives them the same
``ProgramDesc``-as-data treatment the jaxpr lint applies to traced
programs: each protocol declares its state machine — states, initial
state, allowed transitions, and the invariants it promises — as a
:class:`ProtocolSpec` object defined NEXT TO the implementation
(``serving/cluster/replica.py`` declares the replica lifecycle,
``serving/sessions.py`` the session protocol, ...), so a reader of the
code and the model checker read the same artifact.

The spec is load-bearing, not documentation: the explicit-state model
checker (:mod:`.model_check`) tags every world-model action with the
spec transitions it claims to implement, and a step outside the declared
machine is a conformance error — the spec rejects drift the same way an
undeclared metric fails docs/METRICS.md freshness.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple

__all__ = ["Transition", "Invariant", "ProtocolSpec", "register_protocol",
           "registered_protocols", "get_protocol", "load_builtin_specs",
           "SpecError"]


class SpecError(ValueError):
    """A structurally invalid ProtocolSpec (unknown state in a
    transition, duplicate registration, ...)."""


@dataclass(frozen=True)
class Transition:
    """One allowed edge of a protocol state machine."""

    src: str
    action: str
    dst: str

    def __str__(self) -> str:
        return f"{self.src} --{self.action}--> {self.dst}"


@dataclass(frozen=True)
class Invariant:
    """A named safety property the protocol promises; the model checker
    maps each to a state predicate and reports violations under it."""

    name: str
    doc: str


@dataclass(frozen=True)
class ProtocolSpec:
    """A protocol's declared state machine.

    ``states`` is the full state vocabulary, ``initial`` the entry
    state, ``transitions`` the allowed (src, action, dst) edges and
    ``invariants`` the named safety properties.  ``terminal`` states are
    documentation (a process may die in any state; SIGKILL is an
    environment action, not a protocol edge).
    """

    name: str
    description: str
    states: Tuple[str, ...]
    initial: str
    transitions: Tuple[Transition, ...]
    invariants: Tuple[Invariant, ...] = ()
    terminal: Tuple[str, ...] = ()
    module: str = ""

    def __post_init__(self):
        trans = tuple(t if isinstance(t, Transition) else Transition(*t)
                      for t in self.transitions)
        object.__setattr__(self, "transitions", trans)
        invs = tuple(i if isinstance(i, Invariant) else Invariant(*i)
                     for i in self.invariants)
        object.__setattr__(self, "invariants", invs)
        object.__setattr__(self, "states", tuple(self.states))
        object.__setattr__(self, "terminal", tuple(self.terminal))
        known = set(self.states)
        if self.initial not in known:
            raise SpecError(f"{self.name}: initial state "
                            f"{self.initial!r} not in states")
        for t in self.transitions:
            if t.src not in known or t.dst not in known:
                raise SpecError(f"{self.name}: transition {t} references "
                                f"an undeclared state")
        for s in self.terminal:
            if s not in known:
                raise SpecError(f"{self.name}: terminal state {s!r} not "
                                f"in states")

    # -- queries -------------------------------------------------------------
    def allows(self, src: str, action: str, dst: str) -> bool:
        return Transition(src, action, dst) in self.transitions

    def successors(self, src: str) -> Tuple[Transition, ...]:
        return tuple(t for t in self.transitions if t.src == src)

    def actions(self) -> Tuple[str, ...]:
        return tuple(sorted({t.action for t in self.transitions}))

    def as_dict(self) -> dict:
        return {
            "name": self.name, "description": self.description,
            "module": self.module, "states": list(self.states),
            "initial": self.initial, "terminal": list(self.terminal),
            "transitions": [[t.src, t.action, t.dst]
                            for t in self.transitions],
            "invariants": [{"name": i.name, "doc": i.doc}
                           for i in self.invariants],
        }


_REGISTRY: Dict[str, ProtocolSpec] = {}


def register_protocol(spec: ProtocolSpec) -> ProtocolSpec:
    """Register ``spec`` (idempotent for an identical re-registration —
    module reimport must not fail)."""
    prev = _REGISTRY.get(spec.name)
    if prev is not None and prev != spec:
        raise SpecError(f"protocol {spec.name!r} already registered with "
                        f"a different machine")
    _REGISTRY[spec.name] = spec
    return spec


def registered_protocols() -> Dict[str, ProtocolSpec]:
    return dict(_REGISTRY)


def get_protocol(name: str) -> ProtocolSpec:
    if name not in _REGISTRY:
        raise KeyError(
            f"protocol {name!r} is not registered; known: "
            f"{sorted(_REGISTRY)} (did you call load_builtin_specs()?)")
    return _REGISTRY[name]


def load_builtin_specs() -> Dict[str, ProtocolSpec]:
    """Import the serving modules that declare the four cluster
    protocols, populating the registry.  Lazy so that importing
    ``paddle_tpu.analysis`` never drags the serving stack in."""
    import importlib
    for mod in ("paddle_tpu.serving.cluster.replica",
                "paddle_tpu.serving.cluster.router",
                "paddle_tpu.serving.cluster.lifecycle",
                "paddle_tpu.serving.cluster.handoff",
                "paddle_tpu.serving.sessions"):
        importlib.import_module(mod)
    return registered_protocols()
