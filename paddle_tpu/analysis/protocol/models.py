"""Finite world models of the four cluster protocols.

Each model is a faithful small-world abstraction of the real
implementation — 2 replicas, 1 router, 1 controller, 1 session, with
the injected faults the chaos drills use (SIGKILL, drain-hang,
store-write loss) as one-shot environment actions — small enough to
exhaust, rich enough that every seeded bug in
:mod:`.mutations` reaches a violating state.

Timing abstractions (documented, load-bearing):

  * The controller deregisters a cleanly-retired replica synchronously
    with the drain reply (it blocks on the RPC), so heartbeat staleness
    cannot fire inside that window — ``retire`` is one atomic action.
    Heartbeat eviction therefore requires a DEAD process or a ghost
    registration (no tombstone), which is exactly the real monitor's
    miss-count window in the limit.
  * Request completion is abstracted to one in-flight request per
    replica; the router's retry-elsewhere path is a bounce (no state).
  * Session versions are the ``t_park`` keep-newer ordering, bounded to
     3 parks per run (enough to exhibit every stale-replay shape).

Mutations are spelled as string flags (see :mod:`.mutations`): a model
built with a mutation reproduces the seeded bug's behavior; the checker
must then find a violating state — mutation-style validation of the
checker itself.
"""
from __future__ import annotations

from collections import namedtuple
from typing import FrozenSet, Iterable, List, Tuple

from .model_check import ProtocolModel

__all__ = ["ReplicaLifecycleModel", "SessionModel", "RollingUpdateModel",
           "KVHandoffModel", "ALL_MODELS", "build_model"]


def _mut(mutations, name) -> bool:
    return name in mutations


# ---------------------------------------------------------------------------
# replica lifecycle: router + 2 replicas + controller
# ---------------------------------------------------------------------------

Rep = namedtuple("Rep", "phase reg tomb in_rot evicted dereg inflight")
LifeState = namedtuple("LifeState", "reps sigkill hang wloss bad_exec")

_BOOT, _SERVING, _DRAINING, _DRAINED = "boot", "serving", "draining", "drained"
_RETIRED, _WEDGED, _DEAD = "retired", "wedged", "dead"

_RL = "replica-lifecycle"
_RM = "router-membership"


class ReplicaLifecycleModel(ProtocolModel):
    """Boot→serving→draining→retired/evicted across router discovery,
    dispatch, drain orders, tombstones and heartbeat eviction, under
    one-shot SIGKILL / drain-hang / registration-write-loss faults."""

    name = "replica-lifecycle"
    spec_names = (_RL, _RM)

    def __init__(self, n_replicas: int = 2,
                 mutations: FrozenSet[str] = frozenset()):
        self.n = int(n_replicas)
        self.mutations = frozenset(mutations)
        # the stop-accepting flip on drain is the mutation seat: the
        # seeded bug keeps accepting through draining/drained/retired
        if _mut(self.mutations, "lifecycle.accept_while_draining"):
            self._accepts = (_SERVING, _DRAINING, _DRAINED, _RETIRED)
        else:
            self._accepts = (_SERVING,)
        self.invariants = (
            ("dispatch-targets-live",
             "no request is ever EXECUTED by a retired or dead replica "
             "(bounces/transport errors are fine — executions are not)",
             lambda s: not s.bad_exec),
            ("tombstone-evict-exclusive",
             "tombstone-deregister (clean retirement) and heartbeat "
             "eviction are mutually exclusive outcomes for one "
             "registration",
             lambda s: all(not (r.dereg and r.evicted) for r in s.reps)),
            ("no-retire-with-inflight",
             "a replica never retires with a request still in flight "
             "(drain must actually drain before the tombstone lands)",
             lambda s: all(r.phase != _RETIRED or not r.inflight
                           for r in s.reps)),
        )

    def initial_state(self) -> LifeState:
        return LifeState(reps=tuple(
            Rep(_BOOT, False, False, False, False, False, False)
            for _ in range(self.n)),
            sigkill=False, hang=False, wloss=False, bad_exec=False)

    def _with(self, s: LifeState, i: int, **kw) -> Tuple[Rep, ...]:
        reps = list(s.reps)
        reps[i] = reps[i]._replace(**kw)
        return tuple(reps)

    def actions(self, s: LifeState) -> Iterable:
        out: List = []
        mut = self.mutations
        for i, r in enumerate(s.reps):
            # -- boot / registration (store-write loss can eat the
            #    rendezvous record: the replica serves but is never
            #    discovered — tolerated: it simply takes no traffic)
            if r.phase == _BOOT:
                out.append((f"register(r{i})",
                            ((_RL, _BOOT, "register", _SERVING),),
                            s._replace(reps=self._with(
                                s, i, phase=_SERVING, reg=True))))
                if not s.wloss:
                    out.append((f"register_write_lost(r{i})",
                                ((_RL, _BOOT, "register", _SERVING),),
                                s._replace(wloss=True, reps=self._with(
                                    s, i, phase=_SERVING, reg=False))))
            # -- router discovery: skip tombstoned slots; an evicted
            #    handle is remembered (discovery never resurrects it)
            if r.reg and not r.tomb and not r.in_rot and not r.evicted:
                out.append((f"discover(r{i})",
                            ((_RM, "unknown", "discover", "in_rotation"),),
                            s._replace(reps=self._with(s, i, in_rot=True))))
            # -- dispatch: only a replica whose server still ACCEPTS
            #    executes work; everything else bounces (the router
            #    retries elsewhere — not modeled, no state change)
            if r.in_rot and not r.inflight and r.phase in self._accepts:
                bad = r.phase in (_RETIRED, _DEAD)
                out.append((f"dispatch(r{i})", (),
                            s._replace(
                                bad_exec=s.bad_exec or bad,
                                reps=self._with(s, i, inflight=True))))
            if r.inflight and r.phase in (_SERVING, _DRAINING, _DRAINED,
                                          _RETIRED):
                out.append((f"complete(r{i})", (),
                            s._replace(reps=self._with(
                                s, i, inflight=False))))
            # -- controller drain order (only for discovered replicas:
            #    the controller drains through the router handle)
            if r.phase == _SERVING and r.in_rot:
                out.append((f"drain(r{i})",
                            ((_RL, _SERVING, "drain", _DRAINING),),
                            s._replace(reps=self._with(
                                s, i, phase=_DRAINING))))
                if not s.hang:
                    out.append((f"drain_hang(r{i})",
                                ((_RL, _SERVING, "drain", _WEDGED),),
                                s._replace(hang=True, reps=self._with(
                                    s, i, phase=_WEDGED))))
            if r.phase == _DRAINING and (not r.inflight or _mut(
                    mut, "lifecycle.accept_while_draining")):
                out.append((f"drain_complete(r{i})",
                            ((_RL, _DRAINING, "drain_complete", _DRAINED),),
                            s._replace(reps=self._with(
                                s, i, phase=_DRAINED))))
            # -- clean retirement: tombstone + deregister, atomic with
            #    the drain reply (see module docstring).  The seeded
            #    bug drops the tombstone store write.
            retire_ok = r.phase == _DRAINED
            if _mut(mut, "lifecycle.retire_undrained"):
                retire_ok = retire_ok or r.phase == _DRAINING
            if retire_ok:
                tomb = not _mut(mut, "lifecycle.drop_tombstone_write")
                out.append((f"retire(r{i})",
                            ((_RL, r.phase, "retire", _RETIRED),
                             (_RM, "in_rotation", "deregister",
                              "deregistered")),
                            s._replace(reps=self._with(
                                s, i, phase=_RETIRED, tomb=tomb,
                                in_rot=False, dereg=True))))
            # -- drain-hang escalation: the controller's timeout kills
            #    and evicts the wedged replica (never deregisters it)
            if r.phase == _WEDGED:
                out.append((f"drain_timeout_evict(r{i})",
                            ((_RL, _WEDGED, "evict", _DEAD),
                             (_RM, "in_rotation", "evict", "evicted")),
                            s._replace(reps=self._with(
                                s, i, phase=_DEAD, in_rot=False,
                                evicted=True, inflight=False))))
            # -- SIGKILL (one-shot): the process dies in place
            if not s.sigkill and r.phase in (_SERVING, _DRAINING,
                                             _DRAINED, _WEDGED):
                out.append((f"sigkill(r{i})",
                            ((_RL, r.phase, "sigkill", _DEAD),),
                            s._replace(sigkill=True, reps=self._with(
                                s, i, phase=_DEAD, inflight=False))))
            # -- heartbeat staleness: a dead process stops beating and
            #    the monitor evicts it; a GHOST (retired without a
            #    tombstone, rediscovered) goes the same way — which is
            #    exactly what the exclusivity invariant catches
            if r.in_rot and r.phase in (_DEAD, _RETIRED):
                out.append((f"heartbeat_stale_evict(r{i})",
                            ((_RM, "in_rotation", "evict", "evicted"),),
                            s._replace(reps=self._with(
                                s, i, in_rot=False, evicted=True))))
        return out


# ---------------------------------------------------------------------------
# session: active -> parked -> migrating -> restored, over 2 replicas
# ---------------------------------------------------------------------------

SessState = namedtuple(
    "SessState",
    "sphase r0 r1 s0 s1 wire lastw lastw_to p0 p1 aff clobbered excused "
    "sk_used")

_SS = "session"
_UP, _DRN, _GONE = "up", "draining", "gone"


class SessionModel(ProtocolModel):
    """One session over 2 replicas: turn park/restore, drain-time park,
    router-driven export/import migration with move semantics and the
    keep-newer rule, duplicate wire delivery, and replica SIGKILL.

    Versions model ``t_park``: -1 = absent, otherwise monotonically
    increasing park stamps (bounded to 3)."""

    name = "session"
    spec_names = (_SS,)

    def __init__(self, mutations: FrozenSet[str] = frozenset()):
        self.mutations = frozenset(mutations)
        self.invariants = (
            ("one-owner",
             "a session never has two owners (RAM copies + wire blob + "
             "active slots), and reaches zero owners only through a "
             "SIGKILL loss the protocol documents as re-prefill "
             "degradation — never through a clean drain",
             self._inv_one_owner),
            ("no-stale-clobber",
             "an import never overwrites a fresher parked copy with an "
             "older snapshot (the t_park keep-newer rule)",
             lambda s: not s.clobbered),
        )

    @staticmethod
    def _owners(s: SessState) -> int:
        return ((s.r0 >= 0) + (s.r1 >= 0) + (s.s0 >= 0) + (s.s1 >= 0)
                + (s.wire >= 0))

    def _inv_one_owner(self, s: SessState) -> bool:
        n = self._owners(s)
        return n == 1 or (n == 0 and s.excused)

    def initial_state(self) -> SessState:
        # born active: mid-turn in replica 0's decode slot, version 0
        return SessState(sphase="active", r0=-1, r1=-1, s0=0, s1=-1,
                         wire=-1, lastw=-1, lastw_to=-1, p0=_UP, p1=_UP,
                         aff=0, clobbered=False, excused=False,
                         sk_used=False)

    def actions(self, s: SessState) -> Iterable:
        out: List = []
        mut = self.mutations
        rams = (s.r0, s.r1)
        slots = (s.s0, s.s1)
        phases = (s.p0, s.p1)

        def upd(**kw):
            return s._replace(**kw)

        def set_ram(i, v):
            return {"r0": v} if i == 0 else {"r1": v}

        def set_slot(i, v):
            return {"s0": v} if i == 0 else {"s1": v}

        for i in range(2):
            ram, slot, ph = rams[i], slots[i], phases[i]
            # -- turn end: park the active row (version bumps)
            if slot >= 0 and ph == _UP and slot + 1 <= 3:
                out.append((f"park(r{i})",
                            ((_SS, s.sphase, "park", "parked"),),
                            upd(sphase="parked", aff=i,
                                **set_slot(i, -1),
                                **set_ram(i, slot + 1))))
            # -- next turn: take() claims the parked copy into a slot
            if ram >= 0 and slot < 0 and ph == _UP:
                out.append((f"restore(r{i})",
                            ((_SS, "parked", "restore", "restored"),),
                            upd(sphase="restored",
                                **set_ram(i, -1), **set_slot(i, ram))))
            # -- drain: park the active row mid-generation.  The seeded
            #    bug skips the park — the row's state dies with the slot.
            if ph == _UP:
                kw = {("p0" if i == 0 else "p1"): _DRN}
                if slot >= 0:
                    if _mut(mut, "sessions.skip_park_on_drain"):
                        kw.update(set_slot(i, -1))   # dropped, not parked
                        out.append((f"drain_drop(r{i})",
                                    ((_SS, s.sphase, "park", "parked"),),
                                    upd(sphase="parked", **kw)))
                    else:
                        kw.update(set_slot(i, -1))
                        kw.update(set_ram(i, min(slot + 1, 3)))
                        out.append((f"drain_park(r{i})",
                                    ((_SS, s.sphase, "park", "parked"),),
                                    upd(sphase="parked", aff=i, **kw)))
                else:
                    out.append((f"drain(r{i})", (), upd(**kw)))
            # -- migration export off a draining replica: move
            #    semantics (serialize-and-remove).  The seeded bug
            #    copies instead of moving.
            if ph == _DRN and ram >= 0 and s.wire < 0:
                kw = {"wire": ram, "lastw": ram, "lastw_to": 1 - i}
                if not _mut(mut, "sessions.export_copies"):
                    kw.update(set_ram(i, -1))
                out.append((f"export(r{i})",
                            ((_SS, "parked", "export", "migrating"),),
                            upd(sphase="migrating", **kw)))
            # -- SIGKILL (one-shot): RAM + slot copies die with the
            #    process; the documented degradation is a re-prefill
            if not s.sk_used and ph != _GONE:
                kw = {("p0" if i == 0 else "p1"): _GONE, "sk_used": True}
                lost = ram >= 0 or slot >= 0
                kw.update(set_ram(i, -1))
                kw.update(set_slot(i, -1))
                if lost:
                    kw["excused"] = True
                if s.aff == i:
                    kw["aff"] = -1
                out.append((f"sigkill(r{i})", (), upd(**kw)))

        # -- migration import into the target replica (keep-newer)
        if s.wire >= 0:
            j = s.lastw_to
            if j >= 0 and phases[j] == _UP:
                prev = rams[j]
                if prev > s.wire and not _mut(
                        mut, "sessions.import_ignores_newer"):
                    out.append((f"import_dropped_stale(r{j})",
                                ((_SS, "migrating", "import", "parked"),),
                                upd(sphase="parked", wire=-1)))
                else:
                    kw = {"wire": -1, "aff": j}
                    kw.update(set_ram(j, s.wire))
                    if prev > s.wire:
                        kw["clobbered"] = True
                    out.append((f"import(r{j})",
                                ((_SS, "migrating", "import", "parked"),),
                                upd(sphase="parked", **kw)))
        # -- duplicate delivery of the last wire blob (network replay
        #    of the session_import RPC).  Clean keep-newer makes it a
        #    no-op; the seeded bug clobbers the fresher park.
        elif s.lastw >= 0 and s.lastw_to >= 0 \
                and phases[s.lastw_to] == _UP \
                and s.s0 < 0 and s.s1 < 0:
            j = s.lastw_to
            prev = rams[j]
            if prev > s.lastw:
                if _mut(mut, "sessions.import_ignores_newer"):
                    kw = set_ram(j, s.lastw)
                    out.append((f"import_replay(r{j})", (),
                                upd(clobbered=True, **kw)))
                else:
                    out.append((f"import_replay_dropped(r{j})", (), s))
        return [a for a in out if a[2] != s]


# ---------------------------------------------------------------------------
# rolling update: canary -> promote | rollback, journaled replacement
# ---------------------------------------------------------------------------

RollState = namedtuple(
    "RollState",
    "canary old0 old1 new0 new1 promoted rep0 rep1 done rolled_back "
    "mismatch promoted_bad sk_used")

_RU = "rolling-update"


class RollingUpdateModel(ProtocolModel):
    """Canary gate, promote-or-rollback, and the journaled
    spawn-before-drain replacement loop, with controller crash/resume
    implicit (every action's enabling condition is derived from the
    journal + live set, exactly like ``RolloutJournal.resumable_for``),
    a one-shot replacement SIGKILL, and the canary bit-mismatch fault."""

    name = "rolling-update"
    spec_names = (_RU,)

    def __init__(self, mutations: FrozenSet[str] = frozenset()):
        self.mutations = frozenset(mutations)
        self.invariants = (
            ("journal-implies-applied",
             "a journal-committed replacement step is never half-applied:"
             " replaced[i] implies old i retired AND its replacement was "
             "spawned (crash+resume must find the step done)",
             lambda s: all(
                 (not rep) or (old == "retired" and new != "absent")
                 for rep, old, new in ((s.rep0, s.old0, s.new0),
                                       (s.rep1, s.old1, s.new1)))),
            ("spawn-before-drain",
             "an old replica is only retired after its replacement was "
             "spawned (capacity never pays for the update)",
             lambda s: all(
                 old != "retired" or new != "absent"
                 for old, new in ((s.old0, s.new0), (s.old1, s.new1)))),
            ("no-mismatched-promotion",
             "a canary that failed the logits bit-match gate is never "
             "promoted into rotation",
             lambda s: not s.promoted_bad),
            ("rollback-is-clean",
             "a rolled-back update leaves the old fleet serving and "
             "nothing of the new version behind",
             lambda s: not s.rolled_back or (
                 not s.promoted and s.new0 == "absent"
                 and s.new1 == "absent" and s.old0 == "serving"
                 and s.old1 == "serving")),
        )

    def initial_state(self) -> RollState:
        return RollState(canary="absent", old0="serving", old1="serving",
                         new0="absent", new1="absent", promoted=False,
                         rep0=False, rep1=False, done=False,
                         rolled_back=False, mismatch=False,
                         promoted_bad=False, sk_used=False)

    def actions(self, s: RollState) -> Iterable:
        out: List = []
        mut = self.mutations
        if s.done:
            return out
        # arm the canary bit-mismatch fault before the canary spawns
        if s.canary == "absent" and not s.mismatch:
            out.append(("arm_canary_mismatch", (),
                        s._replace(mismatch=True)))
        if s.canary == "absent":
            out.append(("spawn_canary",
                        ((_RU, "idle", "spawn_canary", "canary_gate"),),
                        s._replace(
                            canary="bad" if s.mismatch else "ok")))
        # the gate: bit-match passes -> promote; fails -> rollback.
        # The seeded bug promotes without consulting the gate.
        if s.canary == "ok" or (s.canary == "bad" and _mut(
                mut, "rollout.skip_canary_gate")):
            out.append(("promote_canary",
                        ((_RU, "canary_gate", "promote", "promoting"),),
                        s._replace(canary="promoted", promoted=True,
                                   promoted_bad=s.canary == "bad")))
        if s.canary == "bad":
            out.append(("rollback",
                        ((_RU, "canary_gate", "rollback", "rolled_back"),),
                        s._replace(canary="absent", done=True,
                                   rolled_back=True)))
        if s.promoted:
            for i, (old, new, rep) in enumerate(
                    ((s.old0, s.new0, s.rep0), (s.old1, s.new1, s.rep1))):
                def up(i=i, **kw):
                    if i == 0:
                        kw = {("old0" if k == "old" else
                               "new0" if k == "new" else "rep0"): v
                              for k, v in kw.items()}
                    else:
                        kw = {("old1" if k == "old" else
                               "new1" if k == "new" else "rep1"): v
                              for k, v in kw.items()}
                    return s._replace(**kw)
                if new == "absent" and not rep:
                    out.append((f"spawn_replacement({i})",
                                ((_RU, "promoting", "replace_step",
                                  "promoting"),),
                                up(new="serving")))
                # clean gate: replacement serving before the old
                # replica drains; the seeded bug drains first
                can_retire = old == "serving" and (
                    new == "serving"
                    or _mut(mut, "rollout.drain_before_spawn"))
                if can_retire:
                    out.append((f"retire_old({i})",
                                ((_RU, "promoting", "replace_step",
                                  "promoting"),),
                                up(old="retired")))
                # journal commit AFTER the step is applied; the seeded
                # bug commits first (crash -> resume skips the step)
                if not rep:
                    applied = old == "retired" and new != "absent"
                    if applied or _mut(mut, "rollout.commit_before_apply"):
                        out.append((f"journal_commit({i})",
                                    ((_RU, "promoting", "replace_step",
                                      "promoting"),),
                                    up(rep=True)))
                if new == "serving" and not s.sk_used:
                    out.append((f"sigkill_replacement({i})", (),
                                up(new="dead")._replace(sk_used=True)))
                if new == "dead":
                    out.append((f"respawn_replacement({i})", (),
                                up(new="serving")))
            if s.rep0 and s.rep1:
                out.append(("finish",
                            ((_RU, "promoting", "finish", "complete"),),
                            s._replace(done=True)))
        return out


# ---------------------------------------------------------------------------
# KV handoff: prefill -> wire blob -> decode, exactly-once reply
# ---------------------------------------------------------------------------

HandState = namedtuple(
    "HandState", "req blob P D replies torn_decode retries wloss sk_used")

_KV = "kv-handoff"


class KVHandoffModel(ProtocolModel):
    """One disaggregated request: prefill serializes the KV blob, the
    wire may tear it (store-write loss), decode ingests it behind the
    magic/version integrity check, replicas can be SIGKILLed, the
    router retries retryable failures once."""

    name = "kv-handoff"
    spec_names = (_KV,)

    def __init__(self, mutations: FrozenSet[str] = frozenset()):
        self.mutations = frozenset(mutations)
        self.invariants = (
            ("no-torn-decode",
             "decode never executes over a torn handoff blob (the "
             "magic + header integrity check must reject it)",
             lambda s: not s.torn_decode),
            ("reply-at-most-once",
             "a request is replied to at most once (retries happen only "
             "from retryable-failure states, never after a reply)",
             lambda s: s.replies <= 1),
        )

    def initial_state(self) -> HandState:
        return HandState(req="pending", blob="none", P="up", D="up",
                         replies=0, torn_decode=False, retries=0,
                         wloss=False, sk_used=False)

    def actions(self, s: HandState) -> Iterable:
        out: List = []
        mut = self.mutations
        if s.req == "pending" and s.P == "up":
            out.append(("prefill",
                        ((_KV, "pending", "prefill", "in_flight"),),
                        s._replace(req="in_flight", blob="intact")))
            if not s.wloss:
                out.append(("prefill_blob_torn",
                            ((_KV, "pending", "prefill", "in_flight"),),
                            s._replace(req="in_flight", blob="torn",
                                       wloss=True)))
        if s.req == "in_flight":
            if s.D == "up":
                if s.blob == "intact":
                    out.append(("decode",
                                ((_KV, "in_flight", "decode", "decoded"),),
                                s._replace(req="decoded", blob="none")))
                elif _mut(mut, "handoff.skip_integrity_check"):
                    # the seeded bug decodes whatever bytes arrive
                    out.append(("decode_torn",
                                ((_KV, "in_flight", "decode", "decoded"),),
                                s._replace(req="decoded", blob="none",
                                           torn_decode=True)))
                else:
                    out.append(("reject_torn_blob",
                                ((_KV, "in_flight", "reject", "pending"),)
                                if s.retries < 1 else
                                ((_KV, "in_flight", "fail", "failed"),),
                                s._replace(
                                    req="pending" if s.retries < 1
                                    else "failed",
                                    blob="none",
                                    retries=s.retries + 1)))
            else:
                out.append(("decode_transport_error",
                            ((_KV, "in_flight", "reject", "pending"),)
                            if s.retries < 1 else
                            ((_KV, "in_flight", "fail", "failed"),),
                            s._replace(
                                req="pending" if s.retries < 1
                                else "failed",
                                blob="none", retries=s.retries + 1)))
        if s.req == "decoded":
            out.append(("reply",
                        ((_KV, "decoded", "reply", "replied"),),
                        s._replace(req="replied",
                                   replies=s.replies + 1)))
        # the seeded bug re-dispatches the decode after a reply (a
        # timeout misclassified as a retryable failure)
        if s.req == "replied" and _mut(mut, "handoff.retry_after_reply") \
                and s.D == "up":
            out.append(("re_decode_after_reply", (),
                        s._replace(req="decoded")))
        for name, up in (("P", s.P), ("D", s.D)):
            if up == "up" and not s.sk_used:
                out.append((f"sigkill_{name}", (),
                            s._replace(**{name: "down", "sk_used": True})))
            if up == "down":
                out.append((f"respawn_{name}", (),
                            s._replace(**{name: "up"})))
        return out


ALL_MODELS = {
    "replica-lifecycle": ReplicaLifecycleModel,
    "session": SessionModel,
    "rolling-update": RollingUpdateModel,
    "kv-handoff": KVHandoffModel,
}


def build_model(name: str,
                mutations: FrozenSet[str] = frozenset()) -> ProtocolModel:
    if name not in ALL_MODELS:
        raise KeyError(f"unknown protocol model {name!r}; "
                       f"known: {sorted(ALL_MODELS)}")
    return ALL_MODELS[name](mutations=frozenset(mutations))
