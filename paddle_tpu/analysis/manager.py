"""PassManager: registration, gating, suppression and emission.

Reference parity: paddle/fluid/framework/ir/pass.h + pass_builder — the
~150 framework/inference passes register into a global registry and a
PassBuilder decides which run; severity/suppression here plays the role of
``GetPassesWhiteList``.  The TPU-shape differences:

  * passes are *diagnostic only* (graph-in, findings-out) — rewriting is
    XLA's job; linting runs at trace time where it is amortized per
    compile and costs zero per step;
  * gating is one Python branch (``lint_enabled``) off the
    ``FLAGS_graph_lint`` tri-state ``off|warn|error``, exactly the PR-1
    profiler-gate discipline;
  * findings surface three ways: python warnings (warn mode) or an
    EnforceError (error mode), StatRegistry gauges
    (``graph_lint_warnings`` + per-pass counts), and a LogWriter JSONL
    sink next to the recompile ledger (``FLAGS_graph_lint_dir`` /
    ``PADDLE_TPU_GRAPH_LINT_DIR``).
"""
from __future__ import annotations

import contextlib
import threading
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..framework import flags as _flags
from .diagnostics import (Diagnostic, GraphLintWarning, LintReport,
                          Severity)

_MODES = ("off", "warn", "error")


# ---------------------------------------------------------------------------
# Lint context: everything a pass may inspect about one traced program.
# ---------------------------------------------------------------------------

@dataclass
class LintContext:
    """One traced program + its compile-site metadata.

    ``closed_jaxpr`` is the program body (may be None for pure context
    passes); the rest is optional per-site metadata each pass consults
    when present and skips when absent — a pass must never assume a field
    is populated.
    """

    site: str                                  # compile-cache site name
    kind: str = "cli"                          # jit|executor|train_step|cli|ast
    closed_jaxpr: Any = None
    cache_key: Any = None                      # this compile's cache key
    prev_key: Any = None                       # previous key at this site
    mesh: Any = None                           # jax Mesh (or None)
    donate: Optional[bool] = None              # train-step donation switch
    params: Optional[Dict[str, Any]] = None    # param name -> array/aval
    partition_specs: Optional[Dict[str, Any]] = None  # name -> spec|None
    arg_paths: Optional[List[str]] = None      # names of jaxpr invars
    program_info: Optional[Dict[str, Any]] = None     # static Program view
    ast_root: Any = None                       # dy2static: parsed AST
    filename: Optional[str] = None             # dy2static source file
    firstlineno: int = 1                       # dy2static source offset
    extra: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------

@dataclass
class LintPass:
    pass_id: str
    fn: Callable[[LintContext], List[Diagnostic]]
    severity: Severity
    kinds: Tuple[str, ...]      # context kinds the pass applies to; () = all
    doc: str = ""


class PassManager:
    """Ordered registry of lint passes with per-pass suppression and
    severity overrides (``pass.h`` + pass_builder in one object)."""

    def __init__(self):
        self._passes: Dict[str, LintPass] = {}
        self._severity_override: Dict[str, Severity] = {}

    # -- registration --------------------------------------------------------
    def register(self, pass_id: str, *, severity: Severity = Severity.WARNING,
                 kinds: Tuple[str, ...] = (), doc: str = ""):
        """Decorator registering ``fn(ctx) -> [Diagnostic]`` under
        ``pass_id``.  Re-registration replaces (tests monkey-patch)."""
        def deco(fn):
            self._passes[pass_id] = LintPass(pass_id, fn, severity,
                                             tuple(kinds), doc)
            return fn
        return deco

    def passes(self) -> List[LintPass]:
        return list(self._passes.values())

    def pass_ids(self) -> List[str]:
        return list(self._passes)

    def set_severity(self, pass_id: str, severity: Severity) -> None:
        if pass_id not in self._passes:
            raise KeyError(f"unknown lint pass {pass_id!r}")
        self._severity_override[pass_id] = Severity(severity)

    def severity_of(self, pass_id: str) -> Severity:
        if pass_id in self._severity_override:
            return self._severity_override[pass_id]
        return self._passes[pass_id].severity

    # -- execution -----------------------------------------------------------
    def run(self, ctx: LintContext, suppress=()) -> LintReport:
        """Run every applicable, unsuppressed pass over ``ctx``.  A pass
        that raises is reported as its own WARNING diagnostic — a broken
        lint must never break a compile."""
        suppressed = set(suppress) | _suppressed_ids()
        report = LintReport(site=ctx.site, kind=ctx.kind)
        for p in self._passes.values():
            if p.pass_id in suppressed:
                continue
            if p.kinds and ctx.kind not in p.kinds:
                continue
            try:
                diags = p.fn(ctx) or []
            except Exception as e:   # noqa: BLE001 — lint must not crash
                diags = [Diagnostic(
                    pass_id=p.pass_id, severity=Severity.WARNING,
                    message=f"lint pass crashed: {type(e).__name__}: {e}",
                    site=ctx.site, kind=ctx.kind)]
            sev = self.severity_of(p.pass_id)
            for d in diags:
                d.pass_id = p.pass_id
                d.severity = sev      # pass-level severity (with override)
                d.site = d.site or ctx.site
                d.kind = d.kind or ctx.kind
            report.extend(diags)
        return report


_default_manager = PassManager()


def default_pass_manager() -> PassManager:
    return _default_manager


def register_pass(pass_id: str, *, severity: Severity = Severity.WARNING,
                  kinds: Tuple[str, ...] = (), doc: str = ""):
    """Register onto the default manager (module-level decorator)."""
    return _default_manager.register(pass_id, severity=severity,
                                     kinds=kinds, doc=doc)


# ---------------------------------------------------------------------------
# Gating + suppression
# ---------------------------------------------------------------------------

def lint_mode() -> str:
    """The ``off|warn|error`` tri-state from FLAGS_graph_lint."""
    mode = str(_flags.flag("graph_lint")).lower()
    return mode if mode in _MODES else "off"


def lint_enabled() -> bool:
    """The one off-path branch every integration point checks."""
    return lint_mode() != "off"


_tls = threading.local()


def _suppressed_ids() -> set:
    """Flag-level plus context-manager suppression set."""
    out = {s.strip() for s in
           str(_flags.flag("graph_lint_suppress")).split(",") if s.strip()}
    out |= getattr(_tls, "suppressed", set())
    return out


@contextlib.contextmanager
def suppress(*pass_ids: str):
    """Scoped per-pass suppression::

        with analysis.suppress("layout", "dead-fetch"):
            step(x, y)   # compiles without those passes
    """
    prev = getattr(_tls, "suppressed", set())
    _tls.suppressed = prev | set(pass_ids)
    try:
        yield
    finally:
        _tls.suppressed = prev


# ---------------------------------------------------------------------------
# Emission: gauges + JSONL + warn/raise
# ---------------------------------------------------------------------------

_writer_lock = threading.Lock()
_dir_override: List[Optional[str]] = [None]
_writer: List[Any] = [None, None]   # [dir it was opened for, LogWriter]


def set_lint_dir(path: Optional[str]) -> None:
    """Route lint findings to JSONL under ``path`` (None reverts to the
    ``graph_lint_dir`` flag / PADDLE_TPU_GRAPH_LINT_DIR)."""
    with _writer_lock:
        _dir_override[0] = path
        _get_writer()       # eagerly close/reopen for the new destination


def _get_writer():
    d = _dir_override[0]
    if d is None:
        d = _flags.flag("graph_lint_dir") or None
    if d != _writer[0]:
        if _writer[1] is not None:
            try:
                _writer[1].close()
            except Exception:
                pass
        from ..utils.monitor import LogWriter
        _writer[0] = d
        _writer[1] = LogWriter(logdir=d, filename_suffix=".lint") \
            if d else None
    return _writer[1]


def _gauge_name(pass_id: str) -> str:
    return "graph_lint_" + pass_id.replace("-", "_")


def emit(report: LintReport, mode: Optional[str] = None) -> LintReport:
    """Publish a report: gauges + JSONL always; python warnings in warn
    mode; EnforceError (PreconditionNotMet) in error mode when any finding
    is ERROR-severity.  Returns the report for chaining."""
    from ..utils.monitor import stat_add
    mode = mode or lint_mode()
    if report:
        stat_add("graph_lint_warnings", len(report.diagnostics))
        for pid, n in report.counts().items():
            stat_add(_gauge_name(pid), n)
    with _writer_lock:
        w = _get_writer()
    if w is not None and report:
        for d in report.diagnostics:
            w.add_event("graph_lint/diagnostic", d.as_dict())
    if not report:
        return report
    errors = report.by_severity(Severity.ERROR)
    if mode == "error" and errors:
        from ..framework.enforce import PreconditionNotMetError
        raise PreconditionNotMetError(
            "graph lint failed at trace time (FLAGS_graph_lint=error):\n"
            + "\n".join("  " + str(d) for d in report.diagnostics))
    for d in report.diagnostics:
        warnings.warn(str(d), GraphLintWarning, stacklevel=3)
    return report
