"""PartitionRules: ordered regex-over-param-path -> PartitionSpec tables.

Reference parity: the rule-based partitioning discipline of the JAX LLM
stacks (SNIPPETS.md [1] ``match_partition_rules``: first regex over the
dotted parameter path wins; [3] ``SpecLayout``: one canonical spec per
layer *role*), expressed over THIS repo's mesh axes (parallel.mesh):
``mp`` carries the tensor-parallel split, ``dp``/ZeRO sharding is layered
on afterwards by ``TrainStep._zero_spec`` exactly as for hand
annotations, so one table covers every ZeRO stage.

A :class:`Rule` binds a human-readable *role* (the provenance string every
diagnostic and plan entry carries), a regex matched with ``re.search``
against the dotted parameter path, an optional rank filter (``ndim`` —
how "any 4-d kernel" is expressed without regexing shapes), and the
proposed :class:`~jax.sharding.PartitionSpec`.  ``P()`` is a real rule:
"this role replicates BY DESIGN" is a matched decision, distinct from an
unmatched leaf (which the plan reports and sharding-coverage lints).

Shipped tables (``FLAGS_autoshard_rules`` names them):

  ``transformer``  Megatron-style TP: vocab-sharded embeddings,
                   column-parallel QKV/FFN-in, row-parallel
                   attn-out/FFN-out — byte-for-byte the layout
                   ``text.models.bert.apply_tensor_parallel`` used to
                   hand-annotate.
  ``conv``         conv kernels replicate under TP (data parallel is the
                   conv scaling axis); classifier heads column-shard.
  ``embedding``    recommender tables: embedding matrices vocab-sharded,
                   CTR MLP towers replicated (they scale by data, not TP).
  ``default``      transformer + conv + embedding, in that order.

User escape hatch: :meth:`PartitionRules.with_overrides` prepends rules
(first match wins, so overrides shadow the shipped roles);
:func:`register_rules_table` publishes a custom table under a name the
flag can select.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

__all__ = [
    "Rule", "PartitionRules", "transformer_rules", "conv_rules",
    "embedding_rules", "expert_rules", "default_rules", "rules_table",
    "register_rules_table", "rules_table_names", "active_rules",
    "spec_repr",
]

# the repo's tensor-parallel mesh axis (parallel.mesh.MP_AXIS; literal here
# so importing a rules table never forces the parallel package to load)
MP = "mp"
# the data-parallel axis — mesh-sharded embedding tables (rec.sharded_
# embedding) row-partition over it: CTR meshes are dp-wide, and the
# lookup's all-to-all rides the widest axis
DP = "dp"


def spec_repr(spec: Optional[P]) -> str:
    """Stable human form of a PartitionSpec for plans/diagnostics:
    ``P('mp', None)``; ``None`` (no annotation) prints as ``-``."""
    if spec is None:
        return "-"
    ents = []
    for e in tuple(spec):
        if isinstance(e, (tuple, list)):
            ents.append("(" + ",".join(repr(a) for a in e) + ")")
        else:
            ents.append(repr(e))
    return "P(" + ", ".join(ents) + ")"


@dataclass(frozen=True)
class Rule:
    """One partitioning decision: role name, path regex, optional rank
    filter, proposed spec."""

    role: str
    pattern: str
    spec: P
    ndim: Optional[int] = None      # only match leaves of this rank
    _rx: re.Pattern = field(init=False, repr=False, compare=False,
                            default=None)

    def __post_init__(self):
        object.__setattr__(self, "_rx", re.compile(self.pattern))

    def matches(self, name: str, shape: Sequence[int]) -> bool:
        if self.ndim is not None and len(shape) != self.ndim:
            return False
        return self._rx.search(name) is not None


class PartitionRules:
    """An ORDERED rule table — first match wins (``match_partition_rules``
    semantics), so specific roles go before catch-alls and user overrides
    are prepended."""

    def __init__(self, rules: Iterable[Rule], name: str = "custom"):
        self._rules: Tuple[Rule, ...] = tuple(rules)
        self.name = name
        roles = [r.role for r in self._rules]
        dup = {r for r in roles if roles.count(r) > 1}
        if dup:
            raise ValueError(
                f"rules table {name!r} has duplicate role names {sorted(dup)}"
                " — roles are provenance keys and must be unique")

    # -- lookup --------------------------------------------------------------
    def match(self, name: str, shape: Sequence[int]) -> Optional[Rule]:
        """First rule whose regex (and rank filter) matches the dotted
        parameter path; None when nothing matches."""
        for r in self._rules:
            if r.matches(name, shape):
                return r
        return None

    def spec_for(self, name: str, shape: Sequence[int]) -> Optional[P]:
        r = self.match(name, shape)
        return r.spec if r is not None else None

    # -- composition ---------------------------------------------------------
    def with_overrides(self, rules: Iterable, name: Optional[str] = None
                       ) -> "PartitionRules":
        """New table with ``rules`` PREPENDED (they shadow the shipped
        roles — the user escape hatch).  Each entry is a :class:`Rule` or
        a ``(role, pattern, spec[, ndim])`` tuple."""
        extra = [r if isinstance(r, Rule) else Rule(*r) for r in rules]
        return PartitionRules(extra + list(self._rules),
                              name=name or f"{self.name}+overrides")

    def __iter__(self):
        return iter(self._rules)

    def __len__(self):
        return len(self._rules)

    def roles(self) -> List[str]:
        return [r.role for r in self._rules]

    def __repr__(self):
        return f"PartitionRules({self.name!r}, {len(self._rules)} rules)"


# ---------------------------------------------------------------------------
# shipped canonical tables
# ---------------------------------------------------------------------------

def transformer_rules() -> PartitionRules:
    """Megatron-style TP over ``mp`` for the nn.TransformerEncoder layer
    naming (bert/gpt zoo models).  Linear weights are (in, out), so
    column-parallel = shard dim 1, row-parallel = shard dim 0; Embedding
    weights are (vocab, hidden), vocab-sharded."""
    return PartitionRules([
        Rule("tp-vocab-embedding",
             r"word_embeddings\.weight$|(^|\.)wte\.weight$",
             P(MP, None)),
        Rule("replicated-pos-embedding",
             r"position_embeddings\.weight$|(^|\.)wpe\.weight$"
             r"|token_type_embeddings\.weight$",
             P()),
        Rule("tp-qkv-column",
             r"self_attn\.(q|k|v)_proj\.weight$", P(None, MP)),
        Rule("tp-qkv-bias",
             r"self_attn\.(q|k|v)_proj\.bias$", P(MP)),
        Rule("tp-attn-out-row",
             r"self_attn\.out_proj\.weight$", P(MP, None)),
        Rule("tp-ffn-in-column", r"(^|\.)linear1\.weight$", P(None, MP)),
        Rule("tp-ffn-in-bias", r"(^|\.)linear1\.bias$", P(MP)),
        Rule("tp-ffn-out-row", r"(^|\.)linear2\.weight$", P(MP, None)),
        Rule("replicated-head-dense",
             r"(pooler\.dense|cls\.transform|seq_relationship"
             r"|(^|\.)decoder)\.weight$",
             P()),
    ], name="transformer")


def conv_rules() -> PartitionRules:
    """Conv workloads: kernels replicate under TP (dp/ZeRO is the conv
    scaling axis — TrainStep layers it on); classifier heads
    column-shard over mp."""
    return PartitionRules([
        Rule("conv-kernel-replicated", r"\.weight$", P(), ndim=4),
        Rule("classifier-column",
             r"(^|\.)(fc|head|classifier)(\.\d+)?\.weight$",
             P(None, MP), ndim=2),
        Rule("classifier-bias",
             r"(^|\.)(fc|head|classifier)(\.\d+)?\.bias$", P(MP), ndim=1),
    ], name="conv")


def embedding_rules() -> PartitionRules:
    """Recommender tables: device-resident embedding matrices vocab(row)-
    sharded; CTR MLP towers and wide parts replicate (they scale by data
    and by the PS, not by TP).  ``rec-embedding`` is the mesh-sharded
    table seat (rec.sharded_embedding.ShardedEmbedding stores its table
    under a ``.table`` path): row-partitioned over dp — the all-to-all
    lookup's owner axis — so a table built WITHOUT the layer's own
    annotation still lands the production layout under
    ``FLAGS_autoshard=apply``."""
    return PartitionRules([
        Rule("rec-embedding", r"(^|\.)table$", P(DP, None), ndim=2),
        Rule("row-sharded-embedding",
             r"(^|\.)emb\w*\.weight$|(^|\.)embedding\.weight$",
             P(MP, None), ndim=2),
        Rule("rec-mlp-replicated", r"(^|\.)dnn\.\d+\.(weight|bias)$", P()),
        Rule("rec-wide-replicated", r"(^|\.)wide\w*\.(weight|bias)$", P()),
    ], name="embedding")


def expert_rules() -> PartitionRules:
    """Mixture-of-Experts roles (nn.layer.moe): stacked expert FFN
    planes shard WHOLE experts over the expert-parallel axis (leading
    ``E`` dim — ``P(ep, None, None)``), the gate projection replicates
    (every shard gates its own tokens).  The axis is read from
    ``FLAGS_moe_axis`` at table-construction time so rule proposals
    always agree with the layer's own annotations (default ``ep``;
    ``dp`` for EP=DP meshes)."""
    from ...framework import flags as _flags
    try:
        ep = str(_flags.flag("moe_axis"))
    except KeyError:                         # pragma: no cover - early import
        ep = "ep"
    return PartitionRules([
        Rule("moe-expert-ffn", r"(^|\.)experts\.(w1|w2)$",
             P(ep, None, None), ndim=3),
        Rule("moe-expert-bias", r"(^|\.)experts\.(b1|b2)$",
             P(ep, None), ndim=2),
        Rule("moe-gate-replicated", r"(^|\.)gate\.(weight|bias)$", P()),
    ], name="expert")


def default_rules() -> PartitionRules:
    """The union table every zoo model shards from: expert roles first
    (most specific paths), then transformer, then conv, then
    recommender."""
    return PartitionRules(
        list(expert_rules()) + list(transformer_rules())
        + list(conv_rules()) + list(embedding_rules()),
        name="default")


# ---------------------------------------------------------------------------
# named-table registry (FLAGS_autoshard_rules resolves here)
# ---------------------------------------------------------------------------

_TABLES: Dict[str, Callable[[], PartitionRules]] = {
    "default": default_rules,
    "transformer": transformer_rules,
    "conv": conv_rules,
    "embedding": embedding_rules,
    "expert": expert_rules,
}


def register_rules_table(name: str,
                         factory: Callable[[], PartitionRules]) -> None:
    """Publish a custom table under ``name`` so FLAGS_autoshard_rules
    (and the tools) can select it."""
    if not str(name).strip():
        raise ValueError("rules table name must be non-empty")
    _TABLES[str(name)] = factory


def rules_table_names() -> List[str]:
    return sorted(_TABLES)


def rules_table(name: str) -> PartitionRules:
    """Resolve a table name (shipped or registered) to a fresh table."""
    key = str(name).strip()
    if key not in _TABLES:
        raise KeyError(
            f"unknown autoshard rules table {name!r}; known tables: "
            f"{rules_table_names()} (register_rules_table adds custom ones)")
    return _TABLES[key]()


def active_rules() -> PartitionRules:
    """The table FLAGS_autoshard_rules selects (independent of the
    FLAGS_autoshard mode — sharding-coverage names would-match rules even
    when the transform is off)."""
    from ...framework import flags as _flags
    return rules_table(_flags.flag("autoshard_rules"))
