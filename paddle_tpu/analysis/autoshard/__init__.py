"""paddle_tpu.analysis.autoshard — rules-driven auto-sharding (ISSUE 9).

The analysis family's first *transform* pass: where PR 5's
sharding-coverage lint could only complain that a >=2-d parameter matched
no partition rule, this package ships the rules.  An ordered
regex-over-param-path -> PartitionSpec table (``PartitionRules``, the
SNIPPETS.md [1] ``match_partition_rules`` discipline with [3]'s
canonical-role layout) drives two operations:

  * :func:`propose` — walk a model's param pytree and return a
    :class:`ShardingPlan` with per-leaf rule provenance, an
    unmatched-leaf report and hand-annotation conflicts (read-only);
  * :func:`apply` — write the plan's specs onto the params through
    ``parallel.api.shard_parameter`` (hand annotations always win),
    stamped with provenance so lint can tell rule from hand.

Runtime wiring (off-path = one branch on ``FLAGS_autoshard``
off|propose|apply, env ``PADDLE_TPU_AUTOSHARD``):

  * ``TrainStep.init_state`` calls :func:`maybe_autoshard` before the
    sharding tree is built, so ``FLAGS_autoshard=apply`` shards any zoo
    model from the active ``FLAGS_autoshard_rules`` table with zero
    model-code changes;
  * the ``autoshard-conflict`` lint pass (analysis.passes, ERROR) raises
    at trace time when a rule contradicts a hand annotation; the
    sharding-coverage pass names the rule that *would* match each
    unannotated leaf;
  * ``tools/autoshard.py`` — CLI: propose/apply plans for zoo models
    over virtual meshes and verify applied plans with the PR-8 HLO
    audit (``--strict`` exits non-zero on conflicts or audit ERRORs).

The shipped tables replace hand annotation: ``text.models.bert.
apply_tensor_parallel`` (and gpt's) now delegate here — one transformer
table instead of per-model shard_parameter lists, verified bit-identical.
"""
from __future__ import annotations

from .rules import (Rule, PartitionRules, active_rules,  # noqa: F401
                    conv_rules, default_rules, embedding_rules,
                    expert_rules, register_rules_table, rules_table,
                    rules_table_names, spec_repr, transformer_rules)
from .plan import (LeafPlan, ShardingPlan, propose,  # noqa: F401
                   specs_equivalent)
from .transform import (AUTOSHARD_SOURCE_ATTR, AutoshardWarning,  # noqa: F401
                        apply, autoshard_enabled, autoshard_mode,
                        maybe_autoshard, publish_plan)

__all__ = [
    "Rule", "PartitionRules", "transformer_rules", "conv_rules",
    "embedding_rules", "expert_rules", "default_rules", "rules_table",
    "register_rules_table", "rules_table_names", "active_rules",
    "spec_repr", "LeafPlan", "ShardingPlan", "propose",
    "specs_equivalent", "apply", "maybe_autoshard", "autoshard_mode",
    "autoshard_enabled", "publish_plan", "AutoshardWarning",
    "AUTOSHARD_SOURCE_ATTR",
]
