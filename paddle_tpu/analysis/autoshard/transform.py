"""apply(): the analysis family's first TRANSFORM pass.

Where every pass since PR 5 was read-only (graph-in, findings-out), this
one closes the loop the sharding-coverage lint opened: it takes the plan
``propose`` produced and WRITES the PartitionSpec annotations onto the
model's parameters (``parallel.api.shard_parameter`` — the single
annotation point TrainStep/named_shardings already honor), stamping each
with rule provenance so a later propose/lint can tell rule-applied specs
from hand ones.

Contract (framework/ir rewrite-pass discipline, TPU-shape):

  * hand annotations are NEVER overwritten — a differing hand spec is a
    ``conflict`` in the returned plan, surfaced by the
    ``autoshard-conflict`` lint pass (ERROR at trace time in error mode)
    and by ``tools/autoshard.py --strict``;
  * pure-replication matches (spec ``P()``) annotate nothing — they mark
    the leaf *decided* without touching the param, so a rules-driven
    model stays attribute-identical to the hand-annotated layout it
    replaces (the bit-identity guarantee);
  * re-applying is idempotent: a spec this pass wrote is re-derived, not
    conflicted, even if the table changed (latest table wins).

``maybe_autoshard`` is the one-branch runtime hook TrainStep.init_state
calls: ``FLAGS_autoshard`` ``off`` returns immediately; ``propose``
computes + publishes the plan without mutating; ``apply`` additionally
annotates before the sharding tree is built.
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, Optional

from .plan import AUTOSHARD_SOURCE_ATTR, ShardingPlan, propose
from .rules import PartitionRules, spec_repr

__all__ = [
    "AutoshardWarning", "apply", "maybe_autoshard", "autoshard_mode",
    "autoshard_enabled", "publish_plan", "AUTOSHARD_SOURCE_ATTR",
]

_MODES = ("off", "propose", "apply")


class AutoshardWarning(UserWarning):
    """Conflict/unmatched findings surfaced outside the lint channel."""


def autoshard_mode() -> str:
    """The ``off|propose|apply`` tri-state from FLAGS_autoshard."""
    from ...framework import flags as _flags
    mode = str(_flags.flag("autoshard")).lower()
    return mode if mode in _MODES else "off"


def autoshard_enabled() -> bool:
    """The one off-path branch every integration point checks."""
    return autoshard_mode() != "off"


def apply(layer, *, rules: Optional[PartitionRules] = None, mesh=None,
          plan: Optional[ShardingPlan] = None) -> ShardingPlan:
    """Annotate ``layer``'s parameters from a rules table and return the
    plan (with conflict/unmatched reports).  Hand annotations win; only
    dim-splitting proposals write an attribute."""
    if plan is None:
        plan = propose(layer, rules=rules, mesh=mesh)
    from ...parallel.api import shard_parameter
    by_name = {e.name: e for e in plan.entries}
    for name, p in layer.named_parameters():
        e = by_name.get(name)
        if e is None or e.status != "matched" or e.conflict:
            continue
        if e.existing is not None and e.existing_source is None:
            continue                     # equivalent hand annotation: keep
        if not any(x is not None for x in tuple(e.spec or ())):
            continue                     # pure replication: annotate nothing
        shard_parameter(p, e.spec)
        setattr(p, AUTOSHARD_SOURCE_ATTR, f"{e.table}:{e.rule}")
    return plan


def publish_plan(plan: ShardingPlan, site: str = "autoshard") -> None:
    """Gauges + JSONL (the graph-lint sink) for one plan — the propose
    mode's observable output and the apply mode's audit trail."""
    from ...utils.monitor import stat_add
    stat_add("autoshard_planned", len(plan.sharded))
    stat_add("autoshard_unmatched", len(plan.unmatched))
    stat_add("autoshard_conflicts", len(plan.conflicts))
    from ..manager import _get_writer, _writer_lock
    with _writer_lock:
        w = _get_writer()
    if w is not None:
        w.add_event("autoshard/plan", {"site": site, **plan.as_dict()})


def maybe_autoshard(layer, *, mesh=None, site: str = "autoshard"
                    ) -> Optional[ShardingPlan]:
    """TrainStep's integration hook.  ``off`` = one flag read, nothing
    else.  ``propose`` computes + publishes the plan (no mutation) and
    warns on conflicts; ``apply`` additionally writes the annotations.
    Returns the plan (None when off) so the compile-site lint can reuse
    it without re-matching."""
    mode = autoshard_mode()
    if mode == "off":
        return None
    if mode == "apply":
        plan = apply(layer, mesh=mesh)
    else:
        plan = propose(layer, mesh=mesh)
    publish_plan(plan, site=site)
    for e in plan.conflicts:
        warnings.warn(
            f"autoshard: hand annotation {spec_repr(e.existing)} on "
            f"'{e.name}' contradicts rule '{e.rule}' (table {e.table}) "
            f"proposing {spec_repr(e.spec)}; the hand annotation wins — "
            f"delete it or override the rule "
            f"(PartitionRules.with_overrides)", AutoshardWarning,
            stacklevel=3)
    return plan
