"""ShardingPlan: the propose() side of the auto-sharding transform.

``propose`` walks a model's parameter pytree (a Layer or a plain
``{name: array}`` dict), consults a :class:`~.rules.PartitionRules`
table, and returns a :class:`ShardingPlan` — one :class:`LeafPlan` per
parameter carrying the matched rule's provenance (role + table), the
proposed spec, the *effective* spec after cleaning against the target
mesh, any existing annotation, and whether the two conflict.  Nothing is
mutated: propose is the inspection half; ``transform.apply`` is the
rewrite half.

Leaf discipline (the ``match_partition_rules`` contract, SNIPPETS.md [1],
hardened):

  * scalars (rank 0 or one element) never consult the rules — they
    replicate by construction (``exempt``);
  * 1-d leaves consult the rules (QKV biases DO shard over mp) but an
    unmatched vector is ``exempt``, not an error — vectors replicate by
    design;
  * unmatched >=2-d leaves land in ``plan.unmatched`` — reported, never
    silently defaulted (the sharding-coverage lint names them);
  * a matched leaf with a differing HAND annotation is a ``conflict`` —
    the hand annotation always wins, and the ``autoshard-conflict`` lint
    pass raises it at trace time in error mode.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from .rules import PartitionRules, Rule, spec_repr

__all__ = ["LeafPlan", "ShardingPlan", "propose", "specs_equivalent"]

# annotation-provenance attr stamped by transform.apply (read off Parameter
# objects so a rule-applied spec is never mistaken for a hand one)
AUTOSHARD_SOURCE_ATTR = "_autoshard_rule"


def _norm_spec(spec: Optional[P], mesh=None) -> Tuple:
    """Canonical comparable form of a spec: cleaned against ``mesh`` when
    given (axes the mesh lacks drop — a TP annotation on a pure-DP mesh
    is equivalent to replicated), 1-tuples collapsed, trailing Nones
    stripped.  None (no annotation) normalizes like P() — replicated."""
    if spec is None:
        return ()
    entries = list(tuple(spec))
    if mesh is not None:
        axes = set(getattr(mesh, "shape", {}) or {})
        cleaned = []
        for e in entries:
            if isinstance(e, (tuple, list)):
                kept = tuple(a for a in e if a in axes)
                cleaned.append(kept if kept else None)
            else:
                cleaned.append(e if (e is None or e in axes) else None)
        entries = cleaned
    out = []
    for e in entries:
        if isinstance(e, (tuple, list)):
            e = tuple(e)
            e = e[0] if len(e) == 1 else (None if not e else e)
        out.append(e)
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def specs_equivalent(a: Optional[P], b: Optional[P], mesh=None) -> bool:
    """True when two specs place every dim identically (over ``mesh``
    when given): P(None,'mp') == P(None,('mp',)) == P(None,'mp',None)."""
    return _norm_spec(a, mesh) == _norm_spec(b, mesh)


@dataclass
class LeafPlan:
    """One parameter's row of the plan."""

    name: str
    shape: Tuple[int, ...]
    rule: Optional[str] = None          # matched rule role (provenance)
    table: Optional[str] = None         # rules-table name
    spec: Optional[P] = None            # the rule's proposed spec
    existing: Optional[P] = None        # annotation already on the param
    existing_source: Optional[str] = None  # None = hand; else autoshard role
    status: str = "unmatched"           # matched|hand|exempt|unmatched
    conflict: bool = False              # hand annotation != rule proposal

    @property
    def final_spec(self) -> Optional[P]:
        """The spec the model ends up with: hand annotations win."""
        if self.existing is not None and self.existing_source is None:
            return self.existing
        return self.spec if self.spec is not None else self.existing

    def as_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "shape": list(self.shape),
                "rule": self.rule, "table": self.table,
                "spec": spec_repr(self.spec),
                "existing": spec_repr(self.existing),
                "existing_source": self.existing_source,
                "status": self.status, "conflict": self.conflict}


class ShardingPlan:
    """propose()'s result: per-leaf provenance plus the three reports
    every consumer wants — sharded, unmatched, conflicts."""

    def __init__(self, entries: List[LeafPlan], table: str,
                 mesh_axes: Optional[Dict[str, int]] = None):
        self.entries = entries
        self.table = table
        self.mesh_axes = dict(mesh_axes or {})

    # -- views ---------------------------------------------------------------
    @property
    def matched(self) -> List[LeafPlan]:
        return [e for e in self.entries if e.status == "matched"]

    @property
    def sharded(self) -> List[LeafPlan]:
        """Matched leaves whose proposal actually splits a dim."""
        return [e for e in self.matched
                if any(x is not None for x in tuple(e.spec or ()))]

    @property
    def unmatched(self) -> List[LeafPlan]:
        return [e for e in self.entries if e.status == "unmatched"]

    @property
    def conflicts(self) -> List[LeafPlan]:
        return [e for e in self.entries if e.conflict]

    def specs(self) -> Dict[str, Optional[P]]:
        """{name: final spec} — what apply() would leave on the model."""
        return {e.name: e.final_spec for e in self.entries}

    def entry(self, name: str) -> Optional[LeafPlan]:
        for e in self.entries:
            if e.name == name:
                return e
        return None

    def __len__(self):
        return len(self.entries)

    def __iter__(self):
        return iter(self.entries)

    # -- reports -------------------------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        return {"table": self.table, "mesh_axes": self.mesh_axes,
                "n_leaves": len(self.entries),
                "n_sharded": len(self.sharded),
                "n_matched": len(self.matched),
                "n_unmatched": len(self.unmatched),
                "n_conflicts": len(self.conflicts),
                "entries": [e.as_dict() for e in self.entries]}

    def format(self) -> str:
        head = (f"autoshard plan (table={self.table}, "
                f"mesh={self.mesh_axes or 'none'}): "
                f"{len(self.entries)} leaves, {len(self.sharded)} sharded, "
                f"{len(self.unmatched)} unmatched, "
                f"{len(self.conflicts)} conflict(s)")
        lines = [head]
        for e in self.entries:
            if e.status == "exempt":
                continue
            mark = "!" if e.conflict else (
                "?" if e.status == "unmatched" else " ")
            rule = f"{e.rule}" if e.rule else "(no rule)"
            extra = ""
            if e.existing is not None:
                who = e.existing_source or "hand"
                extra = f"  [existing {who}: {spec_repr(e.existing)}]"
            lines.append(f" {mark} {e.name} {tuple(e.shape)} <- {rule} "
                         f"{spec_repr(e.spec)}{extra}")
        return "\n".join(lines)


def _named_leaves(target, existing, sources):
    """Normalize a Layer / {name: array} target into
    [(name, shape, existing_spec, existing_source, param_obj)]."""
    rows = []
    if isinstance(target, Mapping):
        existing = existing or {}
        sources = sources or {}
        for name in target:
            v = target[name]
            rows.append((name, tuple(getattr(v, "shape", ())),
                         existing.get(name), sources.get(name), None))
        return rows
    # a Layer: read annotations (and their provenance) off the params
    from ...parallel.api import get_partition_spec
    for name, p in target.named_parameters():
        rows.append((name, tuple(p.shape), get_partition_spec(p),
                     getattr(p, AUTOSHARD_SOURCE_ATTR, None), p))
    return rows


def propose(target, *, rules: Optional[PartitionRules] = None,
            mesh=None, existing: Optional[Dict[str, Any]] = None,
            sources: Optional[Dict[str, Optional[str]]] = None
            ) -> ShardingPlan:
    """Walk ``target``'s parameters and produce a full sharding plan.

    ``target`` is an nn.Layer (annotations + provenance read off the
    Parameter objects) or a ``{name: array}`` dict (then ``existing``
    maps names to current specs and ``sources`` to their provenance —
    the lint-pass path, where only arrays survive tracing).
    ``rules=None`` uses the FLAGS_autoshard_rules table; ``mesh=None``
    compares specs raw (no axis cleaning).
    """
    if rules is None:
        from .rules import active_rules
        rules = active_rules()
    entries: List[LeafPlan] = []
    for name, shape, cur, cur_src, _p in _named_leaves(target, existing,
                                                       sources):
        size = 1
        for d in shape:
            size *= int(d)
        if len(shape) == 0 or size <= 1:
            entries.append(LeafPlan(name=name, shape=shape, status="exempt",
                                    existing=cur, existing_source=cur_src))
            continue
        rule = rules.match(name, shape)
        if rule is None:
            status = "exempt" if len(shape) < 2 else "unmatched"
            if cur is not None and cur_src is None:
                status = "hand"      # hand annotation covers the gap
            entries.append(LeafPlan(name=name, shape=shape, status=status,
                                    existing=cur, existing_source=cur_src))
            continue
        conflict = (cur is not None and cur_src is None
                    and not specs_equivalent(cur, rule.spec, mesh))
        entries.append(LeafPlan(
            name=name, shape=shape, rule=rule.role, table=rules.name,
            spec=rule.spec, existing=cur, existing_source=cur_src,
            status="matched", conflict=conflict))
    mesh_axes = dict(getattr(mesh, "shape", {}) or {}) if mesh is not None \
        else {}
    return ShardingPlan(entries, table=rules.name, mesh_axes=mesh_axes)
