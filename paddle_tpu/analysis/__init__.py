"""paddle_tpu.analysis — static analysis over traced programs (graph lint).

Reference parity: paddle/fluid/framework/ir — the ~150 pass registry that
made Fluid's IR *inspectable*: programs were validated, rewritten and
rejected before execution.  The TPU reproduction executes traced jaxprs;
this package closes the inspection gap with a diagnostic pass suite that
runs at trace time over (a) the closed jaxpr captured at jit / Executor /
TrainStep compile and (b) dy2static Python ASTs before transformation.

Wiring (all off-path = one Python branch on ``FLAGS_graph_lint``):

  * always-on cheap passes inside jit/__init__.py, static/executor.py and
    parallel/train_step.py, gated ``off|warn|error``
    (env ``PADDLE_TPU_GRAPH_LINT``);
  * ``tools/graph_lint.py`` — CLI tracing any zoo model in abstract-eval
    mode (no device execution) and emitting a JSON/text report;
  * monitor gauges (``graph_lint_warnings`` + per-pass counts) and a
    LogWriter JSONL sink next to the recompile ledger
    (``FLAGS_graph_lint_dir`` / ``PADDLE_TPU_GRAPH_LINT_DIR``).

Contract: ``off`` adds no per-step work and one branch per compile;
``warn`` emits GraphLintWarning + gauges/JSONL; ``error`` raises
EnforceError (PreconditionNotMet) at trace time when any ERROR-severity
finding fires.  Every pass id is a stable suppression key
(``FLAGS_graph_lint_suppress="layout,dead-fetch"`` or the ``suppress()``
context manager).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .diagnostics import (Diagnostic, GraphLintWarning, LintReport,  # noqa: F401
                          Severity)
from .manager import (LintContext, PassManager, default_pass_manager,  # noqa: F401
                      emit, lint_enabled, lint_mode, register_pass,
                      set_lint_dir, suppress)
from . import passes as _passes  # noqa: F401  (registers the built-ins)
from .passes import PASS_IDS  # noqa: F401
from .ast_lint import (lint_function_ast, lint_jitted_in_file,  # noqa: F401
                       iter_jitted_functions, run_ast_lint)
from . import hlo  # noqa: F401  (compiled-program audit subsystem)
from . import autoshard  # noqa: F401  (rules-driven transform pass)
from . import concurrency_lint  # noqa: F401  (guarded-by / lock-order)
from . import protocol  # noqa: F401  (cluster protocol model checker)

__all__ = [
    "Severity", "Diagnostic", "LintReport", "GraphLintWarning",
    "LintContext", "PassManager", "default_pass_manager",
    "register_pass", "suppress", "set_lint_dir", "lint_mode",
    "lint_enabled", "lint_jaxpr", "lint_traced", "run_ast_lint",
    "lint_function_ast", "lint_jitted_in_file", "iter_jitted_functions",
    "PASS_IDS", "autoshard", "concurrency_lint", "protocol",
]


def lint_jaxpr(closed_jaxpr, *, site: str = "lint", kind: str = "cli",
               suppress=(), **ctx_fields) -> LintReport:
    """Run the pass suite over an already-captured closed jaxpr and return
    the report (no gating, no emission — the inspection API the CLI and
    tests build on)."""
    ctx = LintContext(site=site, kind=kind, closed_jaxpr=closed_jaxpr,
                      **ctx_fields)
    return default_pass_manager().run(ctx, suppress=suppress)


def lint_traced(fn, args, *, site: str, kind: str,
                cache_key: Any = None, prev_key: Any = None,
                donate: Optional[bool] = None,
                params: Optional[Dict[str, Any]] = None,
                partition_specs: Optional[Dict[str, Any]] = None,
                arg_paths=None, mesh=None,
                program_info=None, extra=None) -> Optional[LintReport]:
    """The runtime integration point: abstract-eval ``fn(*args)`` into a
    closed jaxpr (no device execution), run the pass suite, and emit
    through the standard channel.

    Called from the FRESH-compile paths only, behind ``lint_enabled()``,
    so the cost is amortized per XLA compile and is zero per step.  In
    ``error`` mode an ERROR-severity finding raises EnforceError before
    the program ever executes; any *internal* lint failure (an
    untraceable fn) degrades to a single crash diagnostic instead of
    breaking the compile.
    """
    if not lint_enabled():
        return None
    import jax
    from ..framework.tensor import Tensor

    def unwrap(x):
        return x._value if isinstance(x, Tensor) else x

    try:
        closed = jax.make_jaxpr(fn)(*(unwrap(a) for a in args))
    except Exception as e:   # noqa: BLE001 — lint must not break compile
        report = LintReport(site=site, kind=kind)
        report.extend([Diagnostic(
            pass_id="graph-lint", severity=Severity.WARNING,
            message=f"could not abstract-eval the program for linting: "
                    f"{type(e).__name__}: {e}", site=site, kind=kind)])
        return emit(report)
    ctx = LintContext(site=site, kind=kind, closed_jaxpr=closed,
                      cache_key=cache_key, prev_key=prev_key,
                      donate=donate, params=params,
                      partition_specs=partition_specs,
                      arg_paths=list(arg_paths) if arg_paths else None,
                      mesh=mesh, program_info=program_info,
                      extra=dict(extra) if extra else {})
    report = default_pass_manager().run(ctx)
    return emit(report)
