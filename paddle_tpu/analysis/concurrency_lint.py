"""AST concurrency lint over the lock-using serving modules.

The serving plane is host-side lock-and-condvar code (scheduler queues,
session stores, router tables, autoscaler bookkeeping).  The jaxpr
passes can't see it and the chaos drills only sample it; this lint
makes the locking discipline *declared* and then checks it statically:

  * ``# guarded-by: <lock>`` — a trailing comment on a shared-mutable
    field's assignment declares which lock protects it.  Every access
    (read or write) to ``self.<field>`` anywhere in the class must then
    be lexically under ``with self.<lock>:`` — with three deliberate
    outs that match the codebase's conventions:

      - ``__init__``/``__new__`` construct before publication;
      - methods named ``*_locked`` declare "caller holds the lock"
        (``_spill_locked``, ``_drop_affinity_locked``, ...);
      - a private helper whose every call site holds the lock (or is
        itself construction/guarded) inherits the guard — computed as a
        greatest fixpoint over the class's self-call graph, so
        ``_publish_bytes`` called only from guarded methods needs no
        rename.

  * lock-acquisition-order graph — nodes are ``Class.lockattr`` for
    every ``threading.Lock/RLock/Condition`` attribute, edges are
    nested acquisitions (lexical ``with`` nesting plus one level of
    self-calls: a call made while holding A to a method that acquires B
    adds A→B).  Any cycle — including the 1-cycle of re-acquiring a
    non-reentrant lock — is a deadlock hazard.

Deliberate non-goals (documented so findings stay trustworthy): code
inside nested ``def``/``lambda`` is skipped (deferred execution — the
lint cannot know the locks held when it runs); locks reached through
other objects (``with h._lock:`` on a handle) are not graph nodes; the
order graph is per-file.  Zero findings on the real serving tree is a
tier-1 gate (tools/proto_check.py --strict); the seeded mutations in
``analysis/protocol/mutations.py`` prove the detectors fire.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, LintReport, Severity

__all__ = ["CHECKS", "lint_source", "lint_file", "lint_paths",
           "serving_modules", "lint_serving_tree"]

# check inventory (id -> (severity, doc)) — surfaced in docs/LINT.md
CHECKS = {
    "guarded-field": (
        Severity.ERROR,
        "an access to a `# guarded-by:` annotated shared-mutable field "
        "outside its declared lock (not under `with self.<lock>:`, not "
        "in __init__, not in a *_locked method, and not in a private "
        "helper whose every call site holds the lock)"),
    "guard-unknown-lock": (
        Severity.ERROR,
        "a `# guarded-by:` annotation naming an attribute that is not a "
        "recognized threading.Lock/RLock/Condition of the class — the "
        "declaration would silently protect nothing"),
    "lock-order-cycle": (
        Severity.ERROR,
        "a cycle in the lock-acquisition-order graph (nested `with` "
        "blocks plus one level of self-calls), including re-acquiring a "
        "non-reentrant lock — a deadlock hazard two threads can "
        "realize"),
}

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
               "Semaphore": "lock", "BoundedSemaphore": "lock"}


def _lock_ctor_kind(call: ast.AST) -> Optional[str]:
    """'lock'/'rlock'/'condition' when ``call`` is threading.Lock() /
    Lock() / threading.Condition(...) etc., else None."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTORS \
            and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        return _LOCK_CTORS[fn.attr]
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        return _LOCK_CTORS[fn.id]
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@dataclass
class _MethodFacts:
    """Lexically-collected facts about one method body."""

    name: str
    node: ast.AST
    accesses: List[Tuple[str, ast.AST, FrozenSet[str]]] = field(
        default_factory=list)      # (field, node, locks held)
    acquires: List[Tuple[str, ast.AST, FrozenSet[str]]] = field(
        default_factory=list)      # (lock, with-node, locks held before)
    calls: List[Tuple[str, ast.AST, FrozenSet[str]]] = field(
        default_factory=list)      # (callee, node, locks held)


@dataclass
class _ClassFacts:
    name: str
    node: ast.ClassDef
    locks: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    guards: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    methods: Dict[str, _MethodFacts] = field(default_factory=dict)


def _scan_method(cls_locks: Dict[str, Tuple[str, int]],
                 guard_fields: Set[str], meth: ast.AST) -> _MethodFacts:
    facts = _MethodFacts(name=meth.name, node=meth)

    def visit(node: ast.AST, held: FrozenSet[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # deferred execution: skipped (see module docstring)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = []
            for item in node.items:
                visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held)
                lk = _self_attr(item.context_expr)
                if lk in cls_locks:
                    newly.append(lk)
            for lk in newly:
                facts.acquires.append((lk, node, held))
            inner = held | frozenset(newly)
            for stmt in node.body:
                visit(stmt, inner)
            return
        attr = _self_attr(node)
        if attr is not None and attr in guard_fields:
            facts.accesses.append((attr, node, held))
        if isinstance(node, ast.Call):
            callee = _self_attr(node.func)
            if callee is not None:
                facts.calls.append((callee, node, held))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in meth.body:
        visit(stmt, frozenset())
    return facts


def _collect_class(cls: ast.ClassDef, lines: List[str]) -> _ClassFacts:
    out = _ClassFacts(name=cls.name, node=cls)
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # pass 1: lock attributes + guarded-by annotations (annotations live
    # as trailing comments, which ast drops — read the raw source line)
    for meth in methods:
        for node in ast.walk(meth):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                kind = _lock_ctor_kind(value) if value is not None else None
                if kind is not None:
                    out.locks.setdefault(attr, (kind, node.lineno))
                end = getattr(node, "end_lineno", node.lineno)
                for ln in range(node.lineno, min(end, len(lines)) + 1):
                    m = _GUARD_RE.search(lines[ln - 1])
                    if m:
                        out.guards.setdefault(attr, (m.group(1),
                                                     node.lineno))
                        break
    # pass 2: per-method facts
    guard_fields = set(out.guards)
    for meth in methods:
        out.methods[meth.name] = _scan_method(out.locks, guard_fields, meth)
    return out


def _safe_contexts(cf: _ClassFacts) -> Dict[str, Dict[str, bool]]:
    """Greatest fixpoint of safe(method, lock): the method's body may
    touch lock-guarded state without acquiring — because it IS
    construction, declares *_locked, or is a private helper whose every
    call site is itself safe or holds the lock."""
    locks = list(cf.locks)
    sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {
        m: [] for m in cf.methods}
    for caller, facts in cf.methods.items():
        for callee, _node, held in facts.calls:
            if callee in sites:
                sites[callee].append((caller, held))

    def base(name: str) -> Optional[bool]:
        """Fixed verdict, or None for fixpoint-computed methods."""
        if name in ("__init__", "__new__"):
            return True
        if name.endswith("_locked"):
            return True
        if not name.startswith("_") or name.startswith("__"):
            return False            # externally callable: assume nothing
        return None

    safe = {m: {lk: (base(m) if base(m) is not None else True)
                for lk in locks} for m in cf.methods}
    changed = True
    while changed:
        changed = False
        for m in cf.methods:
            if base(m) is not None:
                continue
            for lk in locks:
                if not safe[m][lk]:
                    continue
                ok = bool(sites[m]) and all(
                    lk in held or safe.get(caller, {}).get(lk, False)
                    for caller, held in sites[m])
                if not ok:
                    safe[m][lk] = False
                    changed = True
    return safe


def _loc(filename: str, node: ast.AST) -> str:
    return f"{filename}:{getattr(node, 'lineno', 0)}"


def _guard_diagnostics(cf: _ClassFacts, filename: str,
                       site: str) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for fld, (lock, lineno) in sorted(cf.guards.items()):
        if lock not in cf.locks:
            diags.append(Diagnostic(
                pass_id="guard-unknown-lock", severity=Severity.ERROR,
                message=f"{cf.name}.{fld} declares `guarded-by: {lock}` "
                        f"but {cf.name} has no threading lock attribute "
                        f"named {lock!r}",
                site=site, kind="concurrency",
                location=f"{filename}:{lineno}"))
    known_guards = {f: lk for f, (lk, _ln) in cf.guards.items()
                    if lk in cf.locks}
    if not known_guards:
        return diags
    safe = _safe_contexts(cf)
    for mname, facts in cf.methods.items():
        for fld, node, held in facts.accesses:
            lock = known_guards.get(fld)
            if lock is None:
                continue
            if lock in held or safe[mname].get(lock, False):
                continue
            diags.append(Diagnostic(
                pass_id="guarded-field", severity=Severity.ERROR,
                message=f"{cf.name}.{mname} touches self.{fld} "
                        f"(guarded-by: {lock}) without holding "
                        f"self.{lock} — wrap in `with self.{lock}:`, "
                        f"rename the helper `*_locked`, or call it only "
                        f"under the lock",
                site=site, kind="concurrency",
                location=_loc(filename, node)))
    return diags


def _order_edges(cf: _ClassFacts) -> Dict[Tuple[str, str],
                                          Tuple[str, int, str]]:
    """Directed acquisition-order edges among this class's locks:
    (A, B) -> (filename-agnostic witness: method, lineno, why)."""
    # transitive self-acquisitions: locks a call to m may take
    acq: Dict[str, Set[str]] = {m: {lk for lk, _n, _h in f.acquires}
                                for m, f in cf.methods.items()}
    changed = True
    while changed:
        changed = False
        for m, f in cf.methods.items():
            for callee, _n, _h in f.calls:
                extra = acq.get(callee, set()) - acq[m]
                if extra:
                    acq[m] |= extra
                    changed = True
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for m, f in cf.methods.items():
        for lk, node, held in f.acquires:
            for h in held:
                edges.setdefault(
                    (h, lk),
                    (m, getattr(node, "lineno", 0),
                     f"`with self.{lk}:` nested under self.{h}"))
        for callee, node, held in f.calls:
            for h in held:
                for lk in acq.get(callee, ()):  # call under h takes lk
                    edges.setdefault(
                        (h, lk),
                        (m, getattr(node, "lineno", 0),
                         f"call to self.{callee}() (which acquires "
                         f"self.{lk}) while holding self.{h}"))
    return edges


def _cycles(nodes: Set[str],
            edges: Set[Tuple[str, str]]) -> List[Tuple[str, ...]]:
    """All elementary cycles, canonicalized (rotated to min node)."""
    adj: Dict[str, List[str]] = {n: [] for n in nodes}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    found: Set[Tuple[str, ...]] = set()

    def dfs(start: str, cur: str, path: List[str], seen: Set[str]):
        for nxt in adj.get(cur, ()):  # small graphs — plain DFS is fine
            if nxt == start:
                cyc = tuple(path)
                k = cyc.index(min(cyc))
                found.add(cyc[k:] + cyc[:k])
            elif nxt not in seen and nxt > start:
                # only enumerate cycles from their min node
                dfs(start, nxt, path + [nxt], seen | {nxt})

    for n in sorted(nodes):
        dfs(n, n, [n], {n})
    return sorted(found)


def _order_diagnostics(classes: List[_ClassFacts], filename: str,
                       site: str) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    nodes: Set[str] = set()
    edges: Set[Tuple[str, str]] = set()
    where: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    kinds: Dict[str, str] = {}
    for cf in classes:
        for lk, (kind, _ln) in cf.locks.items():
            nodes.add(f"{cf.name}.{lk}")
            kinds[f"{cf.name}.{lk}"] = kind
        for (a, b), wit in _order_edges(cf).items():
            qa, qb = f"{cf.name}.{a}", f"{cf.name}.{b}"
            edges.add((qa, qb))
            where[(qa, qb)] = wit
    for cyc in _cycles(nodes, edges):
        if len(cyc) == 1 and kinds.get(cyc[0]) == "rlock":
            continue   # re-entrant by construction
        ring = list(cyc) + [cyc[0]]
        steps = []
        lineno = 0
        for a, b in zip(ring, ring[1:]):
            m, ln, why = where.get((a, b), ("?", 0, f"{a} -> {b}"))
            lineno = lineno or ln
            steps.append(f"{m}:{ln} {why}")
        what = ("re-acquisition of non-reentrant" if len(cyc) == 1
                else "acquisition-order cycle among")
        diags.append(Diagnostic(
            pass_id="lock-order-cycle", severity=Severity.ERROR,
            message=f"{what} {' -> '.join(ring)}: " + "; ".join(steps)
                    + " — two threads interleaving these acquisitions "
                      "deadlock",
            site=site, kind="concurrency",
            location=f"{filename}:{lineno}"))
    return diags


def lint_source(source: str, filename: str = "<module>",
                site: str = "") -> List[Diagnostic]:
    """Run the concurrency checks over one module's source text."""
    site = site or f"concurrency:{os.path.basename(filename)}"
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Diagnostic(
            pass_id="guarded-field", severity=Severity.WARNING,
            message=f"could not parse {filename} for concurrency lint: "
                    f"{e}", site=site, kind="concurrency",
            location=f"{filename}:{getattr(e, 'lineno', 0)}")]
    lines = source.splitlines()
    classes = [_collect_class(n, lines) for n in ast.walk(tree)
               if isinstance(n, ast.ClassDef)]
    diags: List[Diagnostic] = []
    for cf in classes:
        diags.extend(_guard_diagnostics(cf, filename, site))
    diags.extend(_order_diagnostics(classes, filename, site))
    return diags


def lint_file(path: str, site: str = "") -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, filename=path, site=site)


def serving_modules(root: Optional[str] = None) -> List[str]:
    """Every .py under paddle_tpu/serving — the lock-using surface the
    tier-1 gate lints (modules without locks or annotations are
    trivially clean)."""
    if root is None:
        root = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "serving")
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for name in sorted(files):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def lint_paths(paths) -> LintReport:
    report = LintReport(site="concurrency", kind="concurrency")
    for p in paths:
        report.extend(lint_file(p))
    return report


def lint_serving_tree(root: Optional[str] = None) -> LintReport:
    return lint_paths(serving_modules(root))
