"""AST-level lint over @to_static Python source, before transformation.

Reference parity: the dy2static transformer pipeline
(dygraph_to_static/ast_transformer.py) runs *validation* visitors before
rewriting — e.g. break_continue/return checks that reject untransformable
source with a pointed error.  Here the same pre-transformation walk flags
TPU hazards visible in the *Python* text that the jaxpr can never show,
because they happen at trace time and leave no equation behind:

  * ``x.numpy()`` / ``x.item()`` / ``x.tolist()`` / ``np.asarray(x)``
    inside a traced function force a device→host transfer per trace (and
    a tracer error or a silently-frozen constant under jit) —
    host-transfer pass, AST flavor.
  * ``float(x)`` / ``int(x)`` on a non-literal: concretizes a traced
    value — recompile-hazard pass, AST flavor (each concretized value can
    bake a new constant into the program).

Findings carry real ``file:line`` provenance from the live AST node plus
the function's source offset, so the warning points at the user's line.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Optional

from .diagnostics import Diagnostic, Severity
from .manager import LintContext, default_pass_manager

_HOST_METHODS = ("numpy", "item", "tolist", "cpu")
_NUMPY_COERCERS = ("asarray", "array")


def _loc(ctx: LintContext, node: ast.AST) -> Optional[str]:
    if ctx.filename is None:
        return None
    line = ctx.firstlineno + getattr(node, "lineno", 1) - 1
    return f"{ctx.filename}:{line}"


class _AstHazardVisitor(ast.NodeVisitor):
    def __init__(self, ctx: LintContext):
        self.ctx = ctx
        self.diagnostics: List[Diagnostic] = []

    def _add(self, pass_id: str, node: ast.AST, message: str):
        self.diagnostics.append(Diagnostic(
            pass_id=pass_id, severity=Severity.WARNING, message=message,
            location=_loc(self.ctx, node), kind="ast"))

    def visit_Call(self, node: ast.Call):
        fn = node.func
        # x.numpy() / x.item() / x.tolist() / x.cpu()
        if isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS:
            self._add(
                "host-transfer", node,
                f".{fn.attr}() inside a @to_static function pulls the "
                f"tensor to HOST at trace time: under jit this either "
                f"errors on the tracer or freezes a stale constant into "
                f"the graph — keep the computation on device or move the "
                f"call outside the compiled function")
        # np.asarray(x) / numpy.array(x)
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _NUMPY_COERCERS
              and isinstance(fn.value, ast.Name)
              and fn.value.id in ("np", "numpy")):
            self._add(
                "host-transfer", node,
                f"{fn.value.id}.{fn.attr}(...) inside a @to_static "
                f"function coerces a traced tensor through HOST numpy — "
                f"use paddle/jnp ops so the value stays in the graph")
        # float(x) / int(x) on a non-literal argument
        elif (isinstance(fn, ast.Name) and fn.id in ("float", "int")
              and node.args
              and not isinstance(node.args[0], ast.Constant)):
            self._add(
                "recompile-hazard", node,
                f"{fn.id}(...) on a traced value concretizes it at trace "
                f"time: each distinct value bakes a new constant into the "
                f"compiled program (a recompile per value) — keep it a "
                f"0-d tensor instead")
        self.generic_visit(node)


def lint_function_ast(fn, site: str = "") -> List[Diagnostic]:
    """Parse ``fn``'s source and run the AST hazard visitor.  Returns raw
    diagnostics (ungated — callers go through ``run_ast_lint`` for flag
    gating/emission).  Unparseable source (REPL lambdas) lints clean."""
    try:
        raw = getattr(fn, "__func__", fn)
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
        filename = inspect.getsourcefile(raw)
        firstlineno = raw.__code__.co_firstlineno
    except (OSError, TypeError, SyntaxError):
        return []
    ctx = LintContext(site=site or f"ast:{getattr(fn, '__qualname__', fn)}",
                      kind="ast", ast_root=tree, filename=filename,
                      firstlineno=firstlineno)
    visitor = _AstHazardVisitor(ctx)
    visitor.visit(tree)
    # route through the manager so per-pass suppression/severity apply
    mgr = default_pass_manager()
    suppressed = set()
    from .manager import _suppressed_ids
    suppressed |= _suppressed_ids()
    out = []
    for d in visitor.diagnostics:
        if d.pass_id in suppressed:
            continue
        try:
            d.severity = mgr.severity_of(d.pass_id)
        except KeyError:
            pass
        d.site = d.site or ctx.site
        out.append(d)
    return out


_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _direct_walk(root: ast.AST):
    """ast.walk that does not descend into nested function bodies (the
    root's own body is walked even when the root is a FunctionDef)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FN_DEFS + (ast.Lambda,)):
            stack.extend(ast.iter_child_nodes(node))


class _JitResolver:
    """Resolve which FunctionDef/Lambda bodies a module hands to
    ``jax.jit``.  The serving/text code never decorates with ``@jit`` —
    it builds closures and jits them at a compile site — so the
    resolver follows the three idioms the repo actually uses, each one
    bounded step of intra-module dataflow:

      * ``jax.jit(call)`` — a local def passed by name;
      * ``fn = self._build_step(...)``; ``jax.jit(fn)`` — a builder
        whose returned inner def is the program (tuple returns and
        tuple-unpack assigns resolve positionally);
      * ``def _compile(self, ..., fn, ...): jax.jit(fn)`` — a compile
        helper whose ``fn`` parameter is bound at each call site.

    Over-approximation is deliberate (every call site of a compile
    helper contributes), under-approximation is possible for flows the
    repo does not use (containers of functions, cross-module builders).
    """

    _MAX_DEPTH = 8

    def __init__(self, tree: ast.AST):
        self.tree = tree
        self.parent_fn = {}
        self.defs_by_name = {}
        stack = [(tree, None)]
        while stack:
            node, fn = stack.pop()
            if isinstance(node, _FN_DEFS):
                self.parent_fn[node] = fn
                self.defs_by_name.setdefault(node.name, []).append(node)
                fn = node
            for child in ast.iter_child_nodes(node):
                stack.append((child, fn))

    def _lookup_def(self, name, scope):
        cands = self.defs_by_name.get(name, [])
        for d in cands:  # innermost match first: defined inside scope
            p = self.parent_fn.get(d)
            while p is not None:
                if p is scope:
                    return d
                p = self.parent_fn.get(p)
        return cands[0] if cands else None

    def resolve(self, expr, scope, idx=None, depth=0, seen=None):
        """Set of FunctionDef/Lambda nodes ``expr`` (evaluated inside
        function ``scope``) may denote; ``idx`` selects a tuple slot of
        a call's return value."""
        seen = set() if seen is None else seen
        key = (id(expr), id(scope), idx)
        if depth > self._MAX_DEPTH or key in seen:
            return set()
        seen.add(key)
        if isinstance(expr, ast.Lambda):
            return {expr}
        if isinstance(expr, ast.IfExp):  # greedy if beam == 1 else beam_
            return (self.resolve(expr.body, scope, idx, depth + 1, seen)
                    | self.resolve(expr.orelse, scope, idx, depth + 1,
                                   seen))
        if isinstance(expr, ast.Name):
            return self._resolve_name(expr.id, scope, idx, depth, seen)
        if isinstance(expr, ast.Call):
            callee = None
            if isinstance(expr.func, ast.Name):
                callee = self._lookup_def(expr.func.id, scope)
            elif isinstance(expr.func, ast.Attribute):
                callee = self._lookup_def(expr.func.attr, scope)
            if callee is None:
                return set()
            return self._resolve_returns(callee, idx, depth + 1, seen)
        return set()

    def _resolve_name(self, name, scope, idx, depth, seen):
        d = self._lookup_def(name, scope)
        if d is not None and idx is None:
            return {d}
        out = set()
        # assignment in the enclosing scopes (module body included):
        # fn = <expr> / a, fn, b = <call>
        scopes, s = [], scope
        while s is not None:
            scopes.append(s)
            s = self.parent_fn.get(s)
        scopes.append(self.tree)
        for s in scopes:
            for node in _direct_walk(s):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        out |= self.resolve(node.value, s, idx,
                                            depth + 1, seen)
                    elif isinstance(tgt, ast.Tuple):
                        for i, el in enumerate(tgt.elts):
                            if isinstance(el, ast.Name) and el.id == name:
                                out |= self.resolve(node.value, s, i,
                                                    depth + 1, seen)
        if out or not isinstance(scope, _FN_DEFS):
            return out
        # parameter of ``scope``: bound at each call site of scope
        params = [a.arg for a in scope.args.args]
        if name not in params:
            return out
        pos = params.index(name)
        for call, call_scope in self._call_sites(scope.name):
            actual, api = None, pos
            if isinstance(call.func, ast.Attribute) and params[:1] == ["self"]:
                api = pos - 1  # self is the receiver, not an argument
            if 0 <= api < len(call.args):
                actual = call.args[api]
            for kw in call.keywords:
                if kw.arg == name:
                    actual = kw.value
            if actual is not None:
                out |= self.resolve(actual, call_scope, idx, depth + 1,
                                    seen)
        return out

    def _call_sites(self, fname):
        """(Call, enclosing FunctionDef) pairs calling ``fname``."""
        stack = [(self.tree, None)]
        while stack:
            node, fn = stack.pop()
            if isinstance(node, _FN_DEFS):
                fn = node
            if isinstance(node, ast.Call):
                f = node.func
                if ((isinstance(f, ast.Name) and f.id == fname) or
                        (isinstance(f, ast.Attribute) and f.attr == fname)):
                    yield node, fn
            for child in ast.iter_child_nodes(node):
                stack.append((child, fn))

    def _resolve_returns(self, fndef, idx, depth, seen):
        out = set()
        for node in _direct_walk(fndef):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            val = node.value
            if idx is not None and isinstance(val, ast.Tuple):
                if idx < len(val.elts):
                    out |= self.resolve(val.elts[idx], fndef, None,
                                        depth, seen)
            else:
                out |= self.resolve(val, fndef, idx, depth, seen)
        return out


def iter_jitted_functions(tree: ast.AST):
    """Yield the ``FunctionDef`` / ``Lambda`` nodes of every function the
    module hands to a ``jit(...)`` / ``jax.jit(...)`` call, following the
    bounded intra-module dataflow documented on :class:`_JitResolver`."""
    res = _JitResolver(tree)
    found, emitted = [], set()
    for call, scope in res._call_sites("jit"):
        if not call.args:
            continue
        for d in sorted(res.resolve(call.args[0], scope),
                        key=lambda n: n.lineno):
            if id(d) not in emitted:
                emitted.add(id(d))
                found.append(d)
    return iter(sorted(found, key=lambda n: n.lineno))


def lint_jitted_in_file(path: str, site: str = "") -> List[Diagnostic]:
    """AST-hazard-lint every jitted function in the module at ``path``.
    Line numbers are module-absolute (the node comes from the full
    module parse), so diagnostics point at the real source line."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    diags: List[Diagnostic] = []
    for node in iter_jitted_functions(tree):
        name = getattr(node, "name", "<lambda>")
        ctx = LintContext(
            site=site or f"ast:{path}:{name}", kind="ast",
            ast_root=node, filename=path, firstlineno=1)
        visitor = _AstHazardVisitor(ctx)
        visitor.visit(node)
        for d in visitor.diagnostics:
            d.site = d.site or ctx.site
        diags.extend(visitor.diagnostics)
    return diags


def run_ast_lint(fn, site: str = ""):
    """Gated entry used by dy2static: lint ``fn``'s source and emit
    through the standard channel (gauges/JSONL/warn/raise)."""
    from .diagnostics import LintReport
    from .manager import emit, lint_enabled
    if not lint_enabled():
        return None
    diags = lint_function_ast(fn, site=site)
    report = LintReport(site=site or f"ast:{getattr(fn, '__qualname__', fn)}",
                        kind="ast")
    report.extend(diags)
    return emit(report)
