"""AST-level lint over @to_static Python source, before transformation.

Reference parity: the dy2static transformer pipeline
(dygraph_to_static/ast_transformer.py) runs *validation* visitors before
rewriting — e.g. break_continue/return checks that reject untransformable
source with a pointed error.  Here the same pre-transformation walk flags
TPU hazards visible in the *Python* text that the jaxpr can never show,
because they happen at trace time and leave no equation behind:

  * ``x.numpy()`` / ``x.item()`` / ``x.tolist()`` / ``np.asarray(x)``
    inside a traced function force a device→host transfer per trace (and
    a tracer error or a silently-frozen constant under jit) —
    host-transfer pass, AST flavor.
  * ``float(x)`` / ``int(x)`` on a non-literal: concretizes a traced
    value — recompile-hazard pass, AST flavor (each concretized value can
    bake a new constant into the program).

Findings carry real ``file:line`` provenance from the live AST node plus
the function's source offset, so the warning points at the user's line.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Optional

from .diagnostics import Diagnostic, Severity
from .manager import LintContext, default_pass_manager

_HOST_METHODS = ("numpy", "item", "tolist", "cpu")
_NUMPY_COERCERS = ("asarray", "array")


def _loc(ctx: LintContext, node: ast.AST) -> Optional[str]:
    if ctx.filename is None:
        return None
    line = ctx.firstlineno + getattr(node, "lineno", 1) - 1
    return f"{ctx.filename}:{line}"


class _AstHazardVisitor(ast.NodeVisitor):
    def __init__(self, ctx: LintContext):
        self.ctx = ctx
        self.diagnostics: List[Diagnostic] = []

    def _add(self, pass_id: str, node: ast.AST, message: str):
        self.diagnostics.append(Diagnostic(
            pass_id=pass_id, severity=Severity.WARNING, message=message,
            location=_loc(self.ctx, node), kind="ast"))

    def visit_Call(self, node: ast.Call):
        fn = node.func
        # x.numpy() / x.item() / x.tolist() / x.cpu()
        if isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS:
            self._add(
                "host-transfer", node,
                f".{fn.attr}() inside a @to_static function pulls the "
                f"tensor to HOST at trace time: under jit this either "
                f"errors on the tracer or freezes a stale constant into "
                f"the graph — keep the computation on device or move the "
                f"call outside the compiled function")
        # np.asarray(x) / numpy.array(x)
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _NUMPY_COERCERS
              and isinstance(fn.value, ast.Name)
              and fn.value.id in ("np", "numpy")):
            self._add(
                "host-transfer", node,
                f"{fn.value.id}.{fn.attr}(...) inside a @to_static "
                f"function coerces a traced tensor through HOST numpy — "
                f"use paddle/jnp ops so the value stays in the graph")
        # float(x) / int(x) on a non-literal argument
        elif (isinstance(fn, ast.Name) and fn.id in ("float", "int")
              and node.args
              and not isinstance(node.args[0], ast.Constant)):
            self._add(
                "recompile-hazard", node,
                f"{fn.id}(...) on a traced value concretizes it at trace "
                f"time: each distinct value bakes a new constant into the "
                f"compiled program (a recompile per value) — keep it a "
                f"0-d tensor instead")
        self.generic_visit(node)


def lint_function_ast(fn, site: str = "") -> List[Diagnostic]:
    """Parse ``fn``'s source and run the AST hazard visitor.  Returns raw
    diagnostics (ungated — callers go through ``run_ast_lint`` for flag
    gating/emission).  Unparseable source (REPL lambdas) lints clean."""
    try:
        raw = getattr(fn, "__func__", fn)
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
        filename = inspect.getsourcefile(raw)
        firstlineno = raw.__code__.co_firstlineno
    except (OSError, TypeError, SyntaxError):
        return []
    ctx = LintContext(site=site or f"ast:{getattr(fn, '__qualname__', fn)}",
                      kind="ast", ast_root=tree, filename=filename,
                      firstlineno=firstlineno)
    visitor = _AstHazardVisitor(ctx)
    visitor.visit(tree)
    # route through the manager so per-pass suppression/severity apply
    mgr = default_pass_manager()
    suppressed = set()
    from .manager import _suppressed_ids
    suppressed |= _suppressed_ids()
    out = []
    for d in visitor.diagnostics:
        if d.pass_id in suppressed:
            continue
        try:
            d.severity = mgr.severity_of(d.pass_id)
        except KeyError:
            pass
        d.site = d.site or ctx.site
        out.append(d)
    return out


def run_ast_lint(fn, site: str = ""):
    """Gated entry used by dy2static: lint ``fn``'s source and emit
    through the standard channel (gauges/JSONL/warn/raise)."""
    from .diagnostics import LintReport
    from .manager import emit, lint_enabled
    if not lint_enabled():
        return None
    diags = lint_function_ast(fn, site=site)
    report = LintReport(site=site or f"ast:{getattr(fn, '__qualname__', fn)}",
                        kind="ast")
    report.extend(diags)
    return emit(report)
