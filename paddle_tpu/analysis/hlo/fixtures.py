"""Seeded negative fixtures for the HLO audit gate.

A gate that has only ever passed clean code proves nothing — these
fixtures construct programs that MUST fail the audit, so CI checks the
detector fires, not merely that the zoo is clean (the same discipline as
testing/faults.py: inject the failure, assert the machinery catches it).
"""
from __future__ import annotations

import numpy as np


def desharded_zero_step(mesh, *, zero: int = 1, feature: int = 128,
                        layers: int = 2):
    """A deliberately DE-SHARDED ZeRO train step: builds a normal
    ``TrainStep(zero=...)`` over ``mesh``, then drops the dp sharding
    annotation from every optimizer accumulator (and, for ``zero>=3``,
    every parameter) — exactly what a refactor that loses the
    ``_zero_spec`` call would do silently.  The compiled executable then
    stores the full state on every device, and the ``hlo-full-gather``
    pass must flag it at ERROR.

    Returns ``(step, inputs, label)`` ready for
    :func:`~.audit.audit_train_step`.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from ...parallel import TrainStep

    class _Probe(nn.Layer):
        """MLP regression net whose weight dims divide any dp degree the
        fixture meshes use (feature=128 covers dp up to 128)."""

        def __init__(self):
            super().__init__()
            self.blocks = nn.LayerList(
                [nn.Linear(feature, feature) for _ in range(layers)])

        def forward(self, x, y):
            h = x
            for blk in self.blocks:
                h = nn.functional.relu(blk(h))
            return ((h - y) ** 2).mean()

    paddle.seed(0)
    model = _Probe()
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-3)
    step = TrainStep(model, opt, mesh=mesh, zero=zero, donate=True)
    state = step.state                      # materialize the honest layout
    rep = NamedSharding(step.mesh, P())

    def deshard(tree_key):
        step._shardings[tree_key] = {
            s: {n: rep for n in acc}
            for s, acc in step._shardings[tree_key].items()
        } if tree_key == "opt" else {
            n: rep for n in step._shardings[tree_key]}
        src = state[tree_key]
        if tree_key == "opt":
            state[tree_key] = {
                s: {n: jax.device_put(np.asarray(v), rep)
                    for n, v in acc.items()}
                for s, acc in src.items()}
        else:
            state[tree_key] = {n: jax.device_put(np.asarray(v), rep)
                               for n, v in src.items()}

    deshard("opt")
    if zero >= 3:
        deshard("params")

    dp = dict(step.mesh.shape).get("dp", 1)
    rng = np.random.RandomState(0)
    x = rng.randn(2 * max(1, dp), feature).astype("float32")
    y = rng.randn(2 * max(1, dp), feature).astype("float32")
    return step, (x, y), None


def desharded_table_step(mesh, *, vocab: int = 1024, emb_dim: int = 8,
                         num_slots: int = 8, dense_dim: int = 4):
    """A deliberately DE-SHARDED embedding-table train step: builds a
    ``ShardedWideDeep`` whose table parameter is annotated
    ``P(axis, None)`` (row-partitioned over the mesh), then drops the
    sharding from the compiled state — the table is stored FULL on every
    device, exactly what a refactor that loses the annotation→layout
    plumbing would do silently.  The ``hlo-full-gather`` pass must flag
    the full-table replication at ERROR (the annotation contract: the
    model says sharded, the executable stores replicated).

    Returns ``(step, inputs, label)`` ready for
    :func:`~.audit.audit_train_step`.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    from ...parallel import TrainStep
    from ...rec.sharded_embedding import ShardedWideDeep

    paddle.seed(0)
    model = ShardedWideDeep(vocab=vocab, emb_dim=emb_dim,
                            num_slots=num_slots, dense_dim=dense_dim,
                            hidden=(16,), mesh=mesh)
    opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                learning_rate=1e-3)
    step = TrainStep(model, opt, mesh=mesh, zero=0, donate=True)
    state = step.state                      # materialize the honest layout
    rep = NamedSharding(step.mesh, P())
    # drop the table's sharding (param + its optimizer accumulators) —
    # the layer's annotation stays, so the audit sees the contradiction
    for name in list(step._shardings["params"]):
        if name.endswith("table"):
            step._shardings["params"][name] = rep
            state["params"][name] = jax.device_put(
                np.asarray(state["params"][name]), rep)
            for s in step._shardings["opt"]:
                if name in step._shardings["opt"][s]:
                    step._shardings["opt"][s][name] = rep
                    state["opt"][s][name] = jax.device_put(
                        np.asarray(state["opt"][s][name]), rep)

    dp = dict(step.mesh.shape).get("dp", 1)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (2 * max(1, dp), num_slots))
    dense = rng.randn(2 * max(1, dp), dense_dim).astype("float32")
    labels = (rng.rand(2 * max(1, dp), 1) > 0.5).astype("float32")
    return step, (ids, dense, labels), None
