"""Compiled-program extraction: what XLA *actually built* for a step.

Everything upstream of here (graph lint, PR 5) inspects the traced jaxpr —
the program the user *wrote*.  This module inspects the program XLA
*compiled*: the post-SPMD-partitioning HLO module of a
``jax.stages.Compiled``, which is where de-sharding, full-gathers of ZeRO
parameters and collective blow-ups become visible (GSPMD inserts the
collectives during partitioning; none of them exist in the jaxpr).

Three extraction surfaces, all read-only and hardware-free (they work on
an abstract CPU lowering exactly as on a real TPU executable):

  * :func:`parse_collectives` / :func:`collective_census` — walk the
    optimized HLO text and count collective ops per kind with per-device
    result bytes and a ring-model wire-byte estimate;
  * :func:`extract_cost` — XLA's own op-level FLOP/byte accounting
    (``compiled.cost_analysis()``, the operators/benchmark/op_tester.cc
    seat) — per-device numbers for an SPMD module;
  * :func:`extract_memory` — per-device argument/output/temp/code sizes
    from ``compiled.memory_analysis()`` (the HBM budget a pod job must
    fit).

:func:`program_stats` bundles all three into one :class:`HloProgramStats`
record — the data the audit passes (audit.py) and the wide-mesh scaling
table (dryrun phase 5 / tools/hlo_audit.py) consume.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = [
    "CollectiveOp", "HloProgramStats", "COLLECTIVE_KINDS",
    "parse_collectives", "collective_census", "extract_cost",
    "extract_memory", "program_stats", "hlo_text",
]

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast")

# bytes per element of an HLO primitive type (token/opaque fall back to 0)
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "tf32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
}

# one collective definition line of an optimized HLO module, e.g.
#   %ar = f32[64,64]{1,0} all-reduce(...), replica_groups=[4,2]<=[8], ...
#   %ag = (f32[8,8]{1,0}, f32[]) all-gather-start(...)
# the (?!-done) keeps the async completion marker from double-counting the
# -start that already carries the shape and groups
_OP_RE = re.compile(
    r"=\s*(?P<result>\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?:-start)?\(")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# iota v2 form: replica_groups=[G,S]<=[...] — G groups of S devices
_GROUPS_V2_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
# literal v1 form: replica_groups={{0,1},{2,3}} — size of the first group
_GROUPS_V1_RE = re.compile(r"replica_groups=\{\{([0-9, ]*)\}")


@dataclass
class CollectiveOp:
    """One collective in the partitioned module.  ``result_bytes`` is the
    PER-DEVICE result size (the partitioned module is the per-device
    program); ``wire_bytes`` is a ring-algorithm estimate of bytes each
    device moves over the interconnect for this op."""

    kind: str
    result_bytes: int
    group_size: int
    wire_bytes: float


def _shape_bytes(result: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(result):
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 0)
    return total


def _wire_factor(kind: str, s: int) -> float:
    """Ring-model interconnect bytes per device, as a multiple of the
    per-device RESULT bytes, for a group of ``s`` devices."""
    if s <= 1:
        return 0.0
    if kind == "all-reduce":            # reduce-scatter + all-gather phases
        return 2.0 * (s - 1) / s
    if kind in ("all-gather", "all-to-all", "collective-broadcast"):
        return (s - 1) / s              # result is the full gathered tensor
    if kind == "reduce-scatter":        # result is one shard of the input
        return float(s - 1)
    return 1.0                          # collective-permute: one hop


def parse_collectives(text: str) -> List[CollectiveOp]:
    """Every collective op of an optimized HLO module text (one entry per
    ``-start`` or sync op; ``-done`` markers carry no shape and are not
    matched)."""
    out: List[CollectiveOp] = []
    for line in text.splitlines():
        m = _OP_RE.search(line)
        if m is None:
            continue
        kind = m.group("kind")
        nbytes = _shape_bytes(m.group("result"))
        g2 = _GROUPS_V2_RE.search(line)
        if g2 is not None:
            size = int(g2.group(2))
        else:
            g1 = _GROUPS_V1_RE.search(line)
            size = (len([x for x in g1.group(1).split(",") if x.strip()])
                    if g1 is not None else 2)
        out.append(CollectiveOp(kind=kind, result_bytes=nbytes,
                                group_size=max(1, size),
                                wire_bytes=nbytes * _wire_factor(kind,
                                                                 size)))
    return out


def collective_census(ops: List[CollectiveOp]) -> Dict[str, Dict[str, float]]:
    """Per-kind {count, result_bytes, wire_bytes} over a parsed op list."""
    census: Dict[str, Dict[str, float]] = {}
    for op in ops:
        row = census.setdefault(op.kind, {"count": 0, "result_bytes": 0,
                                          "wire_bytes": 0.0})
        row["count"] += 1
        row["result_bytes"] += op.result_bytes
        row["wire_bytes"] += op.wire_bytes
    for row in census.values():
        row["wire_bytes"] = round(row["wire_bytes"], 1)
    return census


def hlo_text(compiled) -> Optional[str]:
    """Optimized (post-SPMD) HLO text of a ``jax.stages.Compiled``."""
    try:
        return compiled.as_text()
    except Exception:
        return None


def extract_cost(compiled) -> Dict[str, Any]:
    """XLA cost analysis as a plain dict: per-device ``flops`` and
    ``bytes_accessed`` (algorithmic pre-fusion traffic — an upper bound on
    HBM bytes, see PERF.md round-5), plus availability."""
    try:
        c = compiled.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0]
        return {"available": True,
                "flops": float(c.get("flops", 0.0)),
                "bytes_accessed": float(c.get("bytes accessed", 0.0))}
    except Exception:
        return {"available": False, "flops": 0.0, "bytes_accessed": 0.0}


def extract_memory(compiled) -> Dict[str, Any]:
    """Per-device memory analysis: argument/output/temp/generated-code
    bytes and a peak estimate (args + outputs + temps + code − aliased),
    from ``compiled.memory_analysis()``."""
    try:
        m = compiled.memory_analysis()
        arg = int(m.argument_size_in_bytes)
        out = int(m.output_size_in_bytes)
        tmp = int(m.temp_size_in_bytes)
        code = int(m.generated_code_size_in_bytes)
        alias = int(m.alias_size_in_bytes)
        return {"available": True, "argument_bytes": arg,
                "output_bytes": out, "temp_bytes": tmp,
                "code_bytes": code, "alias_bytes": alias,
                "peak_bytes": max(0, arg + out + tmp + code - alias)}
    except Exception:
        return {"available": False, "argument_bytes": 0, "output_bytes": 0,
                "temp_bytes": 0, "code_bytes": 0, "alias_bytes": 0,
                "peak_bytes": 0}


@dataclass
class HloProgramStats:
    """Everything the audit extracts from one compiled step (per-device
    numbers throughout — the SPMD module is the per-device program)."""

    collectives: Dict[str, Dict[str, float]] = field(default_factory=dict)
    collective_count: int = 0
    collective_result_bytes: int = 0
    collective_wire_bytes: float = 0.0
    cost: Dict[str, Any] = field(default_factory=dict)
    memory: Dict[str, Any] = field(default_factory=dict)
    ops: List[CollectiveOp] = field(default_factory=list)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "collectives": self.collectives,
            "collective_count": self.collective_count,
            "collective_result_bytes": self.collective_result_bytes,
            "collective_wire_bytes": round(self.collective_wire_bytes, 1),
            "flops": self.cost.get("flops", 0.0),
            "bytes_accessed": self.cost.get("bytes_accessed", 0.0),
            "memory": {k: v for k, v in self.memory.items()
                       if k != "available"},
        }


def program_stats(compiled) -> HloProgramStats:
    """One-stop extraction over a compiled executable: collective census
    from the partitioned HLO text + cost analysis + memory analysis."""
    text = hlo_text(compiled) or ""
    ops = parse_collectives(text)
    census = collective_census(ops)
    return HloProgramStats(
        collectives=census,
        collective_count=sum(int(r["count"]) for r in census.values()),
        collective_result_bytes=sum(int(r["result_bytes"])
                                    for r in census.values()),
        collective_wire_bytes=sum(float(r["wire_bytes"])
                                  for r in census.values()),
        cost=extract_cost(compiled),
        memory=extract_memory(compiled),
        ops=ops)
