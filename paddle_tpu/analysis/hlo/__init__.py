"""paddle_tpu.analysis.hlo — compiled-program audit (post-lowering HLO).

The subsystem that closes ROADMAP item 1's inspection gap: PR 5's graph
lint walks the traced jaxpr; this package walks what XLA actually
*compiled* — the post-SPMD-partitioning HLO module of an AOT-lowered step
— where de-sharded ZeRO state, per-step full-gathers and collective
blow-ups first become visible.  Everything is abstract (lower + compile,
no execution), so pod-scale layouts (16/32/64+ devices) are auditable on
a CPU host with ``--xla_force_host_platform_device_count``.

Surfaces:

  * :func:`audit_train_step` / :func:`audit_compiled` — run the hlo pass
    family (hlo-full-gather ERROR, hlo-collective-budget,
    hlo-memory-budget) over a TrainStep / any ``jax.stages.Compiled``;
  * :func:`program_stats` + extract helpers — collective census with
    per-device + ring-model wire bytes, XLA ``cost_analysis()`` FLOPs,
    ``memory_analysis()`` per-device HBM;
  * ``FLAGS_hlo_audit`` off|warn|error (``PADDLE_TPU_HLO_AUDIT``) wires
    the audit into every fresh TrainStep compile, one branch when off;
    findings reuse the PR-5 PassManager severity/suppression machinery;
  * ``tools/hlo_audit.py`` — the CLI face (zoo models over virtual wide
    meshes); ``__graft_entry__.dryrun_multichip`` phase 5 — the
    8/16/32/64-device partitioning gate + scaling table;
  * :func:`fixtures.desharded_zero_step` — the seeded negative fixture
    proving the full-gather detector fires.
"""
from __future__ import annotations

from .extract import (CollectiveOp, HloProgramStats,  # noqa: F401
                      COLLECTIVE_KINDS, collective_census, extract_cost,
                      extract_memory, hlo_text, parse_collectives,
                      program_stats)
from .audit import (HLO_PASS_IDS, HloAuditResult,  # noqa: F401
                    HloAuditWarning, audit_compile_events, audit_compiled,
                    audit_enabled, audit_mode, audit_train_step, emit,
                    hlo_pass_manager, register_hlo_pass, set_audit_dir,
                    state_leaf_table)
from .fixtures import desharded_zero_step  # noqa: F401

__all__ = [
    "CollectiveOp", "HloProgramStats", "COLLECTIVE_KINDS",
    "parse_collectives", "collective_census", "extract_cost",
    "extract_memory", "program_stats", "hlo_text",
    "HLO_PASS_IDS", "HloAuditResult", "HloAuditWarning",
    "hlo_pass_manager", "register_hlo_pass", "audit_mode",
    "audit_enabled", "audit_compiled", "audit_train_step",
    "audit_compile_events", "state_leaf_table", "set_audit_dir", "emit",
    "desharded_zero_step",
]
