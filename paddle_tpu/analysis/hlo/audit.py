"""The HLO audit pass family: pod-scale partitioning hazards, post-lowering.

PR 5's graph lint answers "what did the user trace"; this family answers
"what did XLA compile" — the two questions diverge exactly where pod jobs
die: GSPMD decides during partitioning whether a ZeRO-sharded state leaf
stays sharded or silently materializes (and all-gathers) a full copy per
device, and whether a mesh reshape turns a cheap collective mix into a
blow-up.  The audit runs over an AOT-lowered executable (abstract eval +
XLA compile, NO execution and no hardware), so a 64-device v5e layout is
checkable on a laptop CPU.

Machinery reuse (ISSUE 8 contract): passes register into a
:class:`~..manager.PassManager` (the PR-5 registry — per-pass severity,
``set_severity`` overrides, and the shared suppression surface:
``FLAGS_graph_lint_suppress`` + the scoped ``analysis.suppress()``
context both apply to hlo pass ids).  Gating is its own tri-state
``FLAGS_hlo_audit`` = off|warn|error (env ``PADDLE_TPU_HLO_AUDIT``),
off-path = one Python branch per fresh TrainStep compile; findings
surface as :class:`HloAuditWarning` + ``hlo_audit_*`` gauges + a JSONL
sink (``FLAGS_hlo_audit_dir`` / ``PADDLE_TPU_HLO_AUDIT_DIR``), and error
mode raises EnforceError (PreconditionNotMet) before the step executes.

Pass inventory (ids are stable suppression keys / gauge names):

  hlo-full-gather       ERROR   a ZeRO-sharded state leaf is stored
                                replicated in the compiled executable
                                (the de-shard that turns into a per-step
                                full-gather and a per-device HBM copy)
  hlo-collective-budget WARNING the program is collective-bound: ring-model
                                wire bytes exceed the configured fraction
                                of the program's total byte traffic
  hlo-memory-budget     WARNING per-device peak (args+outputs+temps+code)
                                exceeds the configured HBM budget
"""
from __future__ import annotations

import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...framework import flags as _flags
from ..diagnostics import Diagnostic, GraphLintWarning, LintReport, Severity
from ..manager import LintContext, PassManager
from .extract import HloProgramStats, program_stats

__all__ = [
    "HLO_PASS_IDS", "HloAuditWarning", "HloAuditResult",
    "hlo_pass_manager", "register_hlo_pass", "audit_mode", "audit_enabled",
    "audit_compiled", "audit_train_step", "audit_compile_events",
    "state_leaf_table", "set_audit_dir", "emit",
]

HLO_PASS_IDS = ("hlo-full-gather", "hlo-collective-budget",
                "hlo-memory-budget")
_MODES = ("off", "warn", "error")


class HloAuditWarning(GraphLintWarning):
    """Warn-mode HLO-audit findings (a GraphLintWarning subclass so one
    warnings filter governs both analysis families)."""


_hlo_manager = PassManager()


def hlo_pass_manager() -> PassManager:
    """The HLO audit's own PassManager (separate registry from the trace
    -time lint so kinds/severities never collide; same machinery)."""
    return _hlo_manager


def register_hlo_pass(pass_id: str, *, severity: Severity = Severity.WARNING,
                      kinds: Tuple[str, ...] = ("hlo",), doc: str = ""):
    return _hlo_manager.register(pass_id, severity=severity, kinds=kinds,
                                 doc=doc)


# ---------------------------------------------------------------------------
# Gating
# ---------------------------------------------------------------------------

def audit_mode() -> str:
    mode = str(_flags.flag("hlo_audit")).lower()
    return mode if mode in _MODES else "off"


def audit_enabled() -> bool:
    """The one off-path branch the TrainStep compile site checks."""
    return audit_mode() != "off"


# ---------------------------------------------------------------------------
# State-leaf table: the ZeRO sharding contract vs. the compiled layout
# ---------------------------------------------------------------------------

def _spec_view(sharding) -> Tuple[Optional[Tuple], bool]:
    """(spec entries | None, is_fully_replicated) for any jax sharding."""
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        entries = tuple(spec)
        return entries, not any(e is not None for e in entries)
    try:
        return None, bool(sharding.is_fully_replicated)
    except Exception:
        return None, False


def _leaf_rows(tree_vals, tree_in, tree_out, category, prefix):
    rows = []
    for name in sorted(tree_vals):
        v = tree_vals[name]
        in_spec, in_rep = _spec_view(tree_in[name])
        out_spec, out_rep = _spec_view(tree_out[name])
        rows.append({
            "path": f"{prefix}/{name}", "category": category,
            "shape": tuple(getattr(v, "shape", ())),
            "dtype": str(getattr(v, "dtype", "")),
            "in_spec": in_spec, "in_replicated": in_rep,
            "out_spec": out_spec, "out_replicated": out_rep,
        })
    return rows


def state_leaf_table(state, compiled) -> Optional[List[Dict[str, Any]]]:
    """Flatten the train-step state's params + optimizer accumulators
    against the COMPILED executable's input/output shardings — the ground
    truth of how XLA stores each leaf, independent of any annotation the
    framework *meant* to apply."""
    try:
        in_state = compiled.input_shardings[0][0]
        out_state = compiled.output_shardings[0]
        rows = _leaf_rows(state["params"], in_state["params"],
                          out_state["params"], "param", "params")
        for sname in sorted(state.get("opt", ())):
            rows += _leaf_rows(state["opt"][sname], in_state["opt"][sname],
                               out_state["opt"][sname], "opt",
                               f"opt/{sname}")
        return rows
    except Exception:
        return None       # non-TrainStep layout: the full-gather pass skips


# ---------------------------------------------------------------------------
# Passes
# ---------------------------------------------------------------------------

def _diag(pass_id, message, **extra):
    return Diagnostic(pass_id=pass_id, severity=Severity.WARNING,
                      message=message, extra=extra)


def _has_axis(spec: Optional[Tuple], axis: str) -> bool:
    if spec is None:
        return False
    for e in spec:
        if e == axis or (isinstance(e, (tuple, list)) and axis in e):
            return True
    return False


def _itemsize(dtype: str) -> int:
    try:
        return np.dtype(dtype).itemsize
    except Exception:
        return 4


def _spec_axes(entries) -> set:
    axes = set()
    for e in entries or ():
        if isinstance(e, (tuple, list)):
            axes.update(a for a in e if a)
        elif e is not None:
            axes.add(e)
    return axes


def _annotated_desharded(ctx: LintContext,
                         stats: Optional[HloProgramStats]
                         ) -> List[Diagnostic]:
    """The ANNOTATION contract (sharded-embedding seat, ISSUE 10): a
    parameter the model annotated with a live mesh axis (``P('dp',
    None)`` row-sharded tables, TP layouts) must carry that axis in the
    compiled executable's input AND output shardings.  A leaf that lost
    it is stored full on every device — for a billion-row embedding
    table that is THE failure the partitioning exists to prevent: a
    full-table copy per device plus a full-table all-gather each step."""
    out: List[Diagnostic] = []
    annotated = ctx.extra.get("annotated_specs") or {}
    if not annotated:
        return out
    mesh_axes = dict(ctx.extra.get("mesh_axes") or {})
    leaves = {leaf["path"]: leaf
              for leaf in (ctx.extra.get("state_leaves") or ())}
    for path in sorted(annotated):
        leaf = leaves.get(path)
        if leaf is None:
            continue
        want = {a for a in _spec_axes(annotated[path])
                if mesh_axes.get(a, 1) > 1}
        if not want:
            continue               # annotation names no live axis: moot
        for side in ("in", "out"):
            spec, replicated = leaf[f"{side}_spec"], \
                leaf[f"{side}_replicated"]
            have = _spec_axes(spec)
            if spec is None and not replicated:
                continue           # opaque but sharded: benefit of doubt
            if have & want:
                continue           # honest layout
            shape = leaf["shape"]
            full = int(np.prod(shape)) * _itemsize(leaf["dtype"])
            evidence = 0
            if stats is not None:
                evidence = sum(1 for op in stats.ops
                               if op.kind == "all-gather"
                               and op.result_bytes == full)
            out.append(_diag(
                "hlo-full-gather",
                f"parameter '{path}' {tuple(shape)} is ANNOTATED "
                f"{tuple(annotated[path])} but the compiled executable "
                f"stores it replicated ({side}put sharding "
                f"{spec if spec is not None else 'opaque/replicated'}): "
                f"the {sorted(want)} partition was dropped — every "
                f"device holds the full {full / 1024:.1f} KiB copy and "
                f"the program full-gathers the whole table each step"
                + (f" ({evidence} all-gather op(s) of exactly this size "
                   f"in the partitioned HLO)" if evidence else ""),
                path=path, shape=tuple(shape), side=side,
                full_bytes=full, evidence_gathers=evidence,
                annotated=tuple(str(a) for a in _spec_axes(
                    annotated[path]))))
            break                  # one finding per leaf is enough
    return out


@register_hlo_pass("hlo-full-gather", severity=Severity.ERROR,
                   doc="ZeRO-sharded or annotation-sharded state stored "
                       "replicated in the compiled executable (per-step "
                       "full-gather + per-device full HBM copy)")
def _full_gather(ctx: LintContext) -> List[Diagnostic]:
    """The ZeRO layout contract, re-derived independently and checked
    against the compiled layout: with ``zero>=1`` every optimizer
    accumulator (and with ``zero>=3`` every parameter) that HAS a
    dp-divisible dim left unsharded must carry the dp axis in the
    executable's input AND output sharding.  A leaf that fails is stored
    full on every device — the 'silent de-shard' that multiplies
    per-device HBM by dp and inserts a full all-gather every step.

    Second contract (:func:`_annotated_desharded`): explicitly annotated
    sharded parameters — row-partitioned embedding tables, TP layouts —
    must keep their live annotated axes in the compiled layout,
    independent of any ZeRO stage."""
    stats: Optional[HloProgramStats] = ctx.extra.get("stats")
    out: List[Diagnostic] = list(_annotated_desharded(ctx, stats))
    flagged = {d.extra.get("path") for d in out}
    table = ctx.extra.get("state_leaves") or ()
    dp = int(ctx.extra.get("dp_degree") or 0)
    zero = int(ctx.extra.get("zero") or 0)
    if dp <= 1 or zero < 1:
        return out
    for leaf in table:
        if leaf["path"] in flagged:
            continue
        if leaf["category"] == "opt":
            must = zero >= 1
        else:
            must = zero >= 3
        shape = leaf["shape"]
        if not must or not shape or int(np.prod(shape)) < dp:
            continue
        for side in ("in", "out"):
            spec, replicated = leaf[f"{side}_spec"], \
                leaf[f"{side}_replicated"]
            if spec is not None and _has_axis(spec, "dp"):
                continue              # honest ZeRO layout
            if spec is None and not replicated:
                continue              # opaque but sharded: benefit of doubt
            # the leaf carries no dp shard: is there a dim the ZeRO rule
            # COULD have sharded (free in the spec, divisible by dp)?
            entries = tuple(spec) if spec is not None else (None,) * len(shape)
            entries = entries + (None,) * (len(shape) - len(entries))
            free_div = [d for d in range(len(shape))
                        if entries[d] is None and shape[d] % dp == 0]
            if not free_div:
                continue              # nothing to shard: exempt
            full = int(np.prod(shape)) * _itemsize(leaf["dtype"])
            evidence = 0
            if stats is not None:
                evidence = sum(1 for op in stats.ops
                               if op.kind == "all-gather"
                               and op.result_bytes == full)
            out.append(_diag(
                "hlo-full-gather",
                f"ZeRO-{zero} state leaf '{leaf['path']}' "
                f"{tuple(shape)} is stored REPLICATED in the compiled "
                f"executable ({side}put sharding {spec if spec is not None else 'opaque/replicated'}): "
                f"dim(s) {free_div} divide the dp degree {dp} and should "
                f"be dp-sharded — every device holds the full "
                f"{full / 1024:.1f} KiB copy and the program full-gathers "
                f"it each step"
                + (f" ({evidence} all-gather op(s) of exactly this size "
                   f"in the partitioned HLO)" if evidence else ""),
                path=leaf["path"], shape=tuple(shape), side=side,
                full_bytes=full, evidence_gathers=evidence))
            break                     # one finding per leaf is enough
    return out


@register_hlo_pass("hlo-collective-budget", severity=Severity.WARNING,
                   doc="collective-bound program: interconnect wire bytes "
                       "exceed the budgeted fraction of total traffic")
def _collective_budget(ctx: LintContext) -> List[Diagnostic]:
    stats: Optional[HloProgramStats] = ctx.extra.get("stats")
    if stats is None or not stats.cost.get("available"):
        return []
    total = float(stats.cost.get("bytes_accessed") or 0.0)
    if total <= 0 or stats.collective_wire_bytes <= 0:
        return []
    frac = stats.collective_wire_bytes / total
    budget = float(_flags.flag("hlo_audit_collective_budget"))
    if frac <= budget:
        return []
    return [_diag(
        "hlo-collective-budget",
        f"collective-bound: ring-model wire traffic "
        f"{stats.collective_wire_bytes / 1024:.1f} KiB/step is "
        f"{frac:.2f}x the program's total byte traffic "
        f"({total / 1024:.1f} KiB; budget "
        f"FLAGS_hlo_audit_collective_budget={budget}) — the step will "
        f"scale with the interconnect, not the chip; check the mesh "
        f"shape / sharding mix ({stats.collective_count} collectives: "
        f"{ {k: int(v['count']) for k, v in stats.collectives.items()} })",
        wire_bytes=stats.collective_wire_bytes, bytes_accessed=total,
        fraction=round(frac, 3))]


@register_hlo_pass("hlo-memory-budget", severity=Severity.WARNING,
                   doc="per-device peak memory exceeds the configured HBM "
                       "budget")
def _memory_budget(ctx: LintContext) -> List[Diagnostic]:
    stats: Optional[HloProgramStats] = ctx.extra.get("stats")
    if stats is None or not stats.memory.get("available"):
        return []
    peak = int(stats.memory.get("peak_bytes") or 0)
    budget = float(_flags.flag("hlo_audit_hbm_gb")) * (1 << 30)
    if peak <= budget:
        return []
    m = stats.memory
    return [_diag(
        "hlo-memory-budget",
        f"per-device peak {peak / (1 << 30):.3f} GiB exceeds the HBM "
        f"budget FLAGS_hlo_audit_hbm_gb="
        f"{_flags.flag('hlo_audit_hbm_gb')} (args "
        f"{m['argument_bytes'] / (1 << 20):.1f} MiB + outputs "
        f"{m['output_bytes'] / (1 << 20):.1f} MiB + temps "
        f"{m['temp_bytes'] / (1 << 20):.1f} MiB + code "
        f"{m['code_bytes'] / (1 << 20):.1f} MiB − aliased "
        f"{m['alias_bytes'] / (1 << 20):.1f} MiB): widen the mesh, raise "
        f"the ZeRO stage, or enable remat",
        peak_bytes=peak, budget_bytes=int(budget))]


# ---------------------------------------------------------------------------
# Emission (gauges + JSONL + warn/raise) — hlo_audit's own channel
# ---------------------------------------------------------------------------

_writer_lock = threading.Lock()
_dir_override: List[Optional[str]] = [None]
_writer: List[Any] = [None, None]    # [dir it was opened for, LogWriter]


def set_audit_dir(path: Optional[str]) -> None:
    """Route audit findings to JSONL under ``path`` (None reverts to the
    ``hlo_audit_dir`` flag / PADDLE_TPU_HLO_AUDIT_DIR)."""
    with _writer_lock:
        _dir_override[0] = path
        _get_writer()


def _get_writer():
    d = _dir_override[0]
    if d is None:
        d = _flags.flag("hlo_audit_dir") or None
    if d != _writer[0]:
        if _writer[1] is not None:
            try:
                _writer[1].close()
            except Exception:
                pass
        from ...utils.monitor import LogWriter
        _writer[0] = d
        _writer[1] = LogWriter(logdir=d, filename_suffix=".hlo_audit") \
            if d else None
    return _writer[1]


def emit(report: LintReport, mode: Optional[str] = None) -> LintReport:
    """Publish an audit report: ``hlo_audit_*`` gauges + JSONL always;
    HloAuditWarning in warn mode; EnforceError (PreconditionNotMet) in
    error mode when any finding is ERROR-severity."""
    from ...utils.monitor import stat_add
    mode = mode or audit_mode()
    if report:
        stat_add("hlo_audit_findings", len(report.diagnostics))
        for pid, n in report.counts().items():
            stat_add("hlo_audit_" + pid.replace("-", "_"), n)
    with _writer_lock:
        w = _get_writer()
    if w is not None and report:
        for d in report.diagnostics:
            w.add_event("hlo_audit/diagnostic", d.as_dict())
    if not report:
        return report
    if mode == "error" and report.by_severity(Severity.ERROR):
        from ...framework.enforce import PreconditionNotMetError
        raise PreconditionNotMetError(
            "HLO audit failed on the compiled program "
            "(FLAGS_hlo_audit=error):\n"
            + "\n".join("  " + str(d) for d in report.diagnostics))
    for d in report.diagnostics:
        warnings.warn(str(d), HloAuditWarning, stacklevel=3)
    return report


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

@dataclass
class HloAuditResult:
    """One audit over one compiled executable."""

    site: str
    report: LintReport
    stats: HloProgramStats
    mesh_label: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.report.n_errors == 0

    def as_dict(self) -> Dict[str, Any]:
        return {"site": self.site, "mesh": self.mesh_label,
                "ok": self.ok, "stats": self.stats.as_dict(),
                "findings": self.report.as_dict(), **self.extra}


def audit_compiled(compiled, *, site: str = "hlo", mesh=None, params=None,
                   state=None, zero: int = 0, dp_degree: int = 0,
                   annotated_specs=None, suppress=(), do_emit: bool = True,
                   mesh_label: str = "") -> HloAuditResult:
    """Run the HLO pass family over an already-compiled executable.

    ``state``/``zero``/``dp_degree`` feed the full-gather contract check
    (pass them for train steps; a bare forward audit gets census/budget
    checks only).  ``annotated_specs`` ({'params/<name>': spec-entry
    tuple}) feeds the annotation contract: explicitly sharded params —
    row-partitioned embedding tables, TP layouts — must keep their live
    axes in the compiled layout.  ``do_emit=False`` returns the report
    without gauges / warnings / raising — the CLI and dryrun aggregate
    reports themselves.
    """
    stats = program_stats(compiled)
    extra = {"stats": stats, "zero": int(zero), "dp_degree": int(dp_degree)}
    if annotated_specs:
        extra["annotated_specs"] = dict(annotated_specs)
    if mesh is not None:
        try:
            extra["mesh_axes"] = dict(mesh.shape)
        except Exception:
            pass
    if state is not None:
        extra["state_leaves"] = state_leaf_table(state, compiled)
    ctx = LintContext(site=site, kind="hlo", mesh=mesh, params=params,
                      extra=extra)
    report = _hlo_manager.run(ctx, suppress=suppress)
    res = HloAuditResult(site=site, report=report, stats=stats,
                         mesh_label=mesh_label)
    if do_emit:
        emit(report)
    return res


def _mesh_label(mesh) -> str:
    try:
        return "x".join(f"{a}{n}" for a, n in dict(mesh.shape).items())
    except Exception:
        return ""


def audit_train_step(step, inputs, label=None, *, site: Optional[str] = None,
                     suppress=(), do_emit: bool = True) -> HloAuditResult:
    """AOT-lower a :class:`~...parallel.TrainStep` (no execution), compile
    it, ledger the lowering (kind ``hlo_audit``, mesh-labeled key — the
    ``assert_zero_steady_state_recompiles`` convention extended to audit
    runs) and run the pass family over the executable."""
    from ...profiler import ledger as _ledger
    if not isinstance(inputs, (tuple, list)):
        inputs = (inputs,)
    label_of = _mesh_label(step.mesh)
    site = site or f"hlo_audit:{type(step.layer).__name__}"
    t0 = time.perf_counter()
    compiled = step.aot_compile(inputs, label)
    ms = (time.perf_counter() - t0) * 1e3

    def sig(x):
        if x is None:
            return "none"
        return (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", "")))

    key = (("arg:mesh", label_of),
           ("arg:zero", int(step.zero)),
           ("arg:devices", int(np.prod(list(dict(step.mesh.shape).values())))),
           tuple(sig(x) for x in inputs) + (sig(label),))
    _ledger.record_compile(site, "hlo_audit", key, ms)
    dp = int(dict(step.mesh.shape).get("dp", 1))
    # annotation contract: the specs the MODEL declares (shard_parameter /
    # autoshard provenance) — the executable must not silently drop them
    annotated = {}
    try:
        from ...parallel.api import get_partition_spec
        for name, p in step.layer.named_parameters():
            spec = get_partition_spec(p)
            if spec is not None and any(e is not None for e in tuple(spec)):
                annotated[f"params/{name}"] = tuple(spec)
    except Exception:
        annotated = {}
    return audit_compiled(
        compiled, site=site, mesh=step.mesh, params=step.state["params"],
        state=step.state, zero=step.zero, dp_degree=dp,
        annotated_specs=annotated, suppress=suppress, do_emit=do_emit,
        mesh_label=label_of)


def audit_compile_events() -> List[dict]:
    """Ledger events recorded for audit lowerings (kind ``hlo_audit``) —
    the cross-link that lets steady-state-recompile checks cover audit
    runs: every wide-mesh lowering appears here exactly once, keyed with
    its ``arg:mesh`` label."""
    from ...profiler import ledger as _ledger
    return [e for e in _ledger.compile_events()
            if e.get("kind") == "hlo_audit"]
