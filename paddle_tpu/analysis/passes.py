"""The built-in lint pass suite: TPU perf/correctness hazards at trace time.

Each pass is the TPU seat of a family of reference framework/ir passes
(SURVEY §1): where Fluid's ~150 passes walked the ProgramDesc to validate
ops and rewrite subgraphs before execution, these walk the closed jaxpr
(and compile-site metadata) and *report* — rewriting is XLA's job, but
"this program will recompile every step / round-trip to host / double its
HBM" is knowable before the first step executes, and that is exactly when
it is cheapest to fix.

Pass inventory (ids are stable API — suppression keys, gauge names):

  recompile-hazard        python scalars baked into compile-cache keys,
                          weak-typed operands, shape-varying args
                          (cross-checked against the PR-1 recompile
                          ledger's previous key at the same site)
  host-transfer           callbacks / host round-trips inside the graph
  dtype-promotion         bf16→f32 upcasts on tensors, x64 leaks on TPU
  donation                params/opt-state entering a jitted train step
                          without buffer donation (2× HBM peak)
  layout                  dynamic-slice on minor (tiled) dims; matmul/conv
                          operands badly padded against 8×128 tiling
  collective-consistency  collectives/shard_map over axis names the
                          global mesh does not declare
  dead-fetch              computed-but-unfetched outputs (dead subgraphs)
  sharding-coverage       param leaves no partition rule matched while the
                          mesh has live model-parallel axes
                          (match_partition_rules discipline); names the
                          autoshard rule that WOULD cover each leaf
  autoshard-conflict      a hand shard_parameter annotation contradicts
                          the active autoshard rules table (ERROR: the
                          rules engine and the model disagree about the
                          layout — one of them is wrong)
  cache-key-hygiene       weak-typed or scalar-baked jit invars that
                          fragment the PERSISTENT executable cache key
                          space (jit/persistent_cache.py): what the
                          recompile-hazard pass reports as in-process
                          churn becomes on-disk fan-out — one serialized
                          executable per variant — once
                          FLAGS_executable_cache is on (silent while off)
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .diagnostics import Diagnostic, Severity
from .jaxpr_utils import (all_avals, dead_eqns, iter_eqns, iter_jaxprs,
                          tile_pad_waste, user_source)
from .manager import LintContext, register_pass

__all__ = ["PASS_IDS"]

PASS_IDS = ("recompile-hazard", "host-transfer", "dtype-promotion",
            "donation", "layout", "collective-consistency", "dead-fetch",
            "sharding-coverage", "autoshard-conflict",
            "cache-key-hygiene")


def _diag(pass_id: str, message: str, location: Optional[str] = None,
          **extra) -> Diagnostic:
    return Diagnostic(pass_id=pass_id, severity=Severity.WARNING,
                      message=message, location=location, extra=extra)


def _nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

def _key_leaves(key, path=""):
    """Leaves of a nested cache key, tagged with their positional path —
    the same flattening the recompile ledger diffs with, so the lint and
    the ledger name the same culprit."""
    if isinstance(key, (tuple, list)) and any(
            isinstance(e, (tuple, list, dict)) for e in key):
        for i, e in enumerate(key):
            yield from _key_leaves(e, f"{path}[{i}]")
        return
    yield (path or "·", key)


def _scalar_const_entries(key):
    """('c', <type>, <value>) entries of a jit cache key: python scalars
    baked as static constants — every distinct value is a new program."""
    out = []

    def walk(k, path=""):
        if isinstance(k, (tuple, list)):
            if (len(k) == 3 and k[0] == "c"
                    and k[1] in ("int", "float")):
                out.append((path, k[1], k[2]))
                return
            for i, e in enumerate(k):
                walk(e, f"{path}[{i}]")
    walk(key)
    return out


@register_pass("recompile-hazard", severity=Severity.WARNING,
               doc="cache keys that will churn: scalar constants, "
                   "weak types, shape-varying args")
def _recompile_hazard(ctx: LintContext) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    pid = "recompile-hazard"
    # (1) python scalars baked into the compile-cache key: a changing
    # learning rate / epsilon passed positionally recompiles per value
    if ctx.cache_key is not None:
        for path, tname, val in _scalar_const_entries(ctx.cache_key):
            out.append(_diag(
                pid,
                f"python {tname} {val!r} is baked into the compile-cache "
                f"key at {path}: every distinct value compiles a new "
                f"program — pass it as a Tensor/array operand instead",
                key_path=path))
    # (2) weak-typed operands: a python scalar promoted at trace time
    # carries a weak dtype that jit keys separately from the committed
    # dtype — two silent programs for what looks like the same signature
    if ctx.closed_jaxpr is not None:
        invars, _ = all_avals(ctx.closed_jaxpr)
        for i, aval in enumerate(invars):
            if getattr(aval, "weak_type", False):
                name = (ctx.arg_paths[i]
                        if ctx.arg_paths and i < len(ctx.arg_paths)
                        else f"operand[{i}]")
                out.append(_diag(
                    pid,
                    f"{name} is weak-typed ({aval.dtype}): it was a python "
                    f"scalar at trace time; committing it as a typed array "
                    f"(e.g. np.float32(x)) keeps one stable cache entry",
                    operand=name))
    # (3) ledger cross-check: this site compiled before with a different
    # key — report exactly which entry moved (the ledger's diff), because
    # a per-step moving entry means a recompile per step
    if ctx.prev_key is not None and ctx.cache_key is not None:
        from ..profiler import ledger as _ledger
        for line in _ledger.key_diff(ctx.prev_key, ctx.cache_key):
            if "first compile" in line or "key unchanged" in line:
                continue
            out.append(_diag(
                pid,
                f"this site recompiled: cache-key entry changed — {line}; "
                f"if this argument varies per step (e.g. a growing "
                f"sequence length), pad/bucket it to a stable shape",
                diff=line))
    return out


# ---------------------------------------------------------------------------
# cache-key-hygiene
# ---------------------------------------------------------------------------

def _weak_key_leaves(key):
    """Weak-typed signature leaves of a compile-cache key: both the jit
    signature convention ('t'|'a', shape, dtype, 'weak') and the ledger's
    labeled-leaf convention ('arg:<path>', shape, dtype, 'weak')."""
    out = []

    def walk(k, path=""):
        if isinstance(k, (tuple, list)):
            if len(k) == 4 and k[3] == "weak":
                if k[0] in ("t", "a"):
                    out.append((path or "operand", k[1], k[2]))
                    return
                if isinstance(k[0], str) and k[0].startswith("arg:"):
                    out.append((k[0][4:], k[1], k[2]))
                    return
            for i, e in enumerate(k):
                walk(e, f"{path}[{i}]")
    walk(key)
    return out


@register_pass("cache-key-hygiene", severity=Severity.WARNING,
               doc="weak-typed / scalar-baked jit invars that fragment "
                   "the persistent executable cache key space")
def _cache_key_hygiene(ctx: LintContext) -> List[Diagnostic]:
    """The recompile-hazard findings, re-read through the persistent
    executable cache (jit/persistent_cache.py): a key leaf that churns
    in-process costs a recompile per variant, but under
    FLAGS_executable_cache=readwrite it also SERIALIZES one on-disk
    executable per variant — the cache dir fans out and warm starts stop
    hitting.  Silent (one branch) while the cache flag is off."""
    from ..framework import flags as _flags
    try:
        if str(_flags.flag("executable_cache")).lower() == "off":
            return []
    except KeyError:
        return []
    if ctx.cache_key is None:
        return []
    pid = "cache-key-hygiene"
    out: List[Diagnostic] = []
    for path, tname, val in _scalar_const_entries(ctx.cache_key):
        out.append(_diag(
            pid,
            f"python {tname} {val!r} is baked into the compile key at "
            f"{path}: every distinct value serializes ANOTHER executable "
            f"into FLAGS_executable_cache_dir and none of them load on a "
            f"warm start with a different value — pass it as an array "
            f"operand so one cached entry serves all values",
            key_path=path))
    for path, shape, dtype in _weak_key_leaves(ctx.cache_key):
        out.append(_diag(
            pid,
            f"{path} enters the compile key weak-typed "
            f"({dtype}{list(shape)}): a python scalar at trace time keys "
            f"a DIFFERENT persistent cache entry than the committed "
            f"array a warm start feeds — commit the dtype (e.g. "
            f"np.float32(x)) so cold and warm starts share one entry",
            operand=path))
    # ledger cross-check (the recompile-hazard pass's machinery): a key
    # that already churned at this site is already fanning out on disk
    if ctx.prev_key is not None:
        from ..profiler import ledger as _ledger
        churn = [ln for ln in _ledger.key_diff(ctx.prev_key,
                                               ctx.cache_key)
                 if "first compile" not in ln
                 and "key unchanged" not in ln]
        if churn:
            out.append(_diag(
                pid,
                f"this site's cache key churns ({churn[0]}): each "
                f"variant persists its own executable — the "
                f"recompile-hazard fix (stable shapes/dtypes/buckets) "
                f"is also the disk-footprint fix",
                diff=churn[0]))
    return out


# ---------------------------------------------------------------------------
# host-transfer
# ---------------------------------------------------------------------------

_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call", "device_get",
})


@register_pass("host-transfer", severity=Severity.ERROR,
               doc="host round-trips (callbacks, numpy coercion) inside "
                   "a traced region")
def _host_transfer(ctx: LintContext) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    if ctx.closed_jaxpr is None:
        return out
    for eqn, _ in iter_eqns(ctx.closed_jaxpr):
        name = eqn.primitive.name
        if name in _CALLBACK_PRIMS:
            out.append(_diag(
                "host-transfer",
                f"'{name}' runs on HOST mid-graph: the TPU stalls for a "
                f"device→host→device round-trip every step — move the "
                f"computation in-graph or hoist it out of the compiled "
                f"region",
                user_source(eqn), primitive=name))
    return out


# ---------------------------------------------------------------------------
# dtype-promotion
# ---------------------------------------------------------------------------

_X64_DTYPES = ("float64", "int64", "uint64", "complex128")
_MXU_CONSUMERS = frozenset({"dot_general", "conv_general_dilated"})


@register_pass("dtype-promotion", severity=Severity.WARNING,
               doc="unintended f32 upcasts in a bf16 graph; x64 dtypes "
                   "on TPU")
def _dtype_promotion(ctx: LintContext) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    if ctx.closed_jaxpr is None:
        return out
    pid = "dtype-promotion"
    invars, _ = all_avals(ctx.closed_jaxpr)
    low_precision_graph = any(
        str(getattr(a, "dtype", "")) in ("bfloat16", "float16")
        for a in invars)
    seen = set()
    for jaxpr in iter_jaxprs(ctx.closed_jaxpr):
        # bf16→f32 upcasts that FEED MXU ops: those cost 4× the matmul
        # FLOPs of staying bf16.  Reduction-epilogue upcasts (mean/softmax
        # accumulating in f32) are accumulation precision, not a hazard —
        # only the producer→dot/conv dataflow edge is flagged.
        if low_precision_graph:
            producer = {}
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "convert_element_type":
                    src = eqn.invars[0].aval
                    dst = eqn.outvars[0].aval
                    if (str(src.dtype) in ("bfloat16", "float16")
                            and str(dst.dtype) == "float32"
                            and len(dst.shape) >= 2):
                        producer[eqn.outvars[0]] = eqn
            for eqn in jaxpr.eqns:
                if eqn.primitive.name not in _MXU_CONSUMERS:
                    continue
                for v in eqn.invars:
                    up = producer.get(v)
                    if up is None:
                        continue
                    src = up.invars[0].aval
                    key = (user_source(up), str(src.dtype),
                           tuple(src.shape))
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(_diag(
                        pid,
                        f"{src.dtype}[{','.join(map(str, src.shape))}] is "
                        f"upcast to float32 and fed into "
                        f"'{eqn.primitive.name}': the matmul runs at f32 "
                        f"MXU rate (4× the bf16 cost) and the operand "
                        f"doubles its HBM traffic — keep the operand "
                        f"bf16 (preferred_element_type=f32 accumulates "
                        f"safely), or suppress if this is a deliberate "
                        f"master-weight cast",
                        user_source(up), shape=tuple(src.shape)))
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                dt = str(getattr(aval, "dtype", ""))
                if dt in _X64_DTYPES:
                    key = (user_source(eqn), dt)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(_diag(
                        pid,
                        f"{dt} produced in-graph: TPUs have no 64-bit "
                        f"compute units — XLA emulates it at a multiple "
                        f"of the cost (jax_enable_x64 leak?)",
                        user_source(eqn), dtype=dt))
    return out


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

@register_pass("donation", severity=Severity.ERROR,
               kinds=("train_step",),
               doc="params/opt-state entering a jitted train step without "
                   "buffer donation")
def _donation(ctx: LintContext) -> List[Diagnostic]:
    if ctx.donate is not False:
        return []
    size = 0
    if ctx.params:
        size = sum(_nbytes(v) for v in ctx.params.values())
    mib = size / (1 << 20)
    detail = f" (~{mib:.1f} MiB of parameters alone, before optimizer " \
             f"state)" if size else ""
    return [_diag(
        "donation",
        f"train-step state enters the jitted step WITHOUT buffer "
        f"donation{detail}: XLA must keep both the old and the new "
        f"params/opt-state live across the step — 2× peak HBM. Pass "
        f"donate=True (the default) unless you are aliasing the state "
        f"elsewhere",
        state_bytes=size)]


# ---------------------------------------------------------------------------
# layout
# ---------------------------------------------------------------------------

_MXU_PRIMS = frozenset({"dot_general", "conv_general_dilated"})


@register_pass("layout", severity=Severity.WARNING,
               doc="dynamic-slice on tiled minor dims; matmul/conv "
                   "operands badly padded against 8x128 tiling")
def _layout(ctx: LintContext) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    if ctx.closed_jaxpr is None:
        return out
    pid = "layout"
    seen = set()
    import jax as _jax
    from .jaxpr_utils import static_vars
    for jaxpr in iter_jaxprs(ctx.closed_jaxpr):
        # per-level static set: slice starts that are functions of
        # trace-time constants fold away; only genuinely traced offsets
        # pay the cross-tile gather
        statics = static_vars(jaxpr)

        def _static(v):
            return isinstance(v, _jax.core.Literal) or v in statics

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in ("dynamic_slice", "dynamic_update_slice"):
                operand = eqn.invars[0].aval
                ndim = len(operand.shape)
                if ndim == 0:
                    continue
                if name == "dynamic_slice":
                    sizes = eqn.params.get("slice_sizes", ())
                    starts = eqn.invars[1:]
                else:
                    sizes = eqn.invars[1].aval.shape
                    starts = eqn.invars[2:]
                # minor = the last (lane, 128) and second-to-last
                # (sublane, 8) tiled dims
                for d in range(max(0, ndim - 2), ndim):
                    if d >= len(sizes) or sizes[d] == operand.shape[d]:
                        continue
                    start = starts[d] if d < len(starts) else None
                    if start is None or _static(start):
                        continue
                    if (d == ndim - 2 and len(sizes) == ndim
                            and sizes[ndim - 1] == operand.shape[ndim - 1]):
                        # ring-buffer KV-cache access: a traced start on
                        # the SUBLANE dim with the lane dim fully spanned
                        # lowers to a sublane-masked store/load within
                        # tiles, not a cross-tile gather.  Covers both
                        # the canonical generate() cache append
                        # (dynamic_update_slice, PR 7) and the quantized
                        # KV reads the fused-dequant path issues — int8
                        # rows and per-head scale planes read by
                        # dynamic_slice at the traced cache_position
                        # with their (full) lane extent.  Only a traced
                        # lane-dim start is a hazard
                        continue
                    which = "lane (last)" if d == ndim - 1 else "sublane"
                    key = (user_source(eqn), name, d)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(_diag(
                        pid,
                        f"'{name}' slices the {which} dim of a "
                        f"{operand.dtype}"
                        f"[{','.join(map(str, operand.shape))}] at a "
                        f"dynamic offset: minor dims are tiled 8x128 on "
                        f"TPU, so this lowers to a masked gather across "
                        f"tiles — slice a major dim (transpose first) or "
                        f"use a static offset",
                        user_source(eqn), dim=d))
            elif name in _MXU_PRIMS:
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    shape = tuple(getattr(aval, "shape", ()))
                    if len(shape) < 2 or shape[-1] <= 128:
                        continue
                    waste = tile_pad_waste(shape[-1])
                    if waste <= 0.25:
                        continue
                    key = (user_source(eqn), shape)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(_diag(
                        pid,
                        f"MXU operand "
                        f"{aval.dtype}[{','.join(map(str, shape))}] pads "
                        f"its minor dim {shape[-1]} up to "
                        f"{((shape[-1] + 127) // 128) * 128} lanes "
                        f"({waste:.0%} of the tile wasted): pick a "
                        f"feature dim near a multiple of 128",
                        user_source(eqn), dim=shape[-1],
                        waste=round(waste, 3)))
    return out


# ---------------------------------------------------------------------------
# collective-consistency
# ---------------------------------------------------------------------------

_COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmean", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index", "pgather",
})


def _declared_axes(ctx: LintContext) -> Optional[frozenset]:
    mesh = ctx.mesh
    if mesh is None:
        from ..parallel.mesh import has_mesh, get_mesh
        if not has_mesh():
            return None             # nothing declared -> nothing to check
        mesh = get_mesh()
    return frozenset(str(a) for a in mesh.axis_names)


@register_pass("collective-consistency", severity=Severity.ERROR,
               doc="collectives / shard_map over axis names the global "
                   "mesh does not declare")
def _collective_consistency(ctx: LintContext) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    if ctx.closed_jaxpr is None:
        return out
    declared = _declared_axes(ctx)
    if declared is None:
        return out
    pid = "collective-consistency"
    seen = set()
    for eqn, bound in iter_eqns(ctx.closed_jaxpr):
        name = eqn.primitive.name
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            axes = [str(a) for a in getattr(mesh, "axis_names", ())]
            unknown = [a for a in axes if a not in declared]
            if unknown:
                key = (user_source(eqn), tuple(unknown))
                if key not in seen:
                    seen.add(key)
                    out.append(_diag(
                        pid,
                        f"shard_map binds mesh axes {unknown} that the "
                        f"global mesh does not declare (declared: "
                        f"{sorted(declared)}): its collectives will run "
                        f"over a private device grouping — rebuild the "
                        f"region over the global mesh axes",
                        user_source(eqn), axes=unknown))
        elif name in _COLLECTIVE_PRIMS:
            axes = eqn.params.get("axes",
                                  eqn.params.get("axis_name", ()))
            if not isinstance(axes, (tuple, list)):
                axes = (axes,)
            named = [a for a in axes if isinstance(a, str)]
            unknown = [a for a in named
                       if a not in declared and a not in bound]
            if unknown:
                key = (user_source(eqn), name, tuple(unknown))
                if key not in seen:
                    seen.add(key)
                    out.append(_diag(
                        pid,
                        f"'{name}' reduces over axis name(s) {unknown} "
                        f"declared by neither the global mesh "
                        f"({sorted(declared)}) nor any enclosing "
                        f"shard_map/pmap: the collective cannot bind — "
                        f"check the axis_name spelling against the mesh",
                        user_source(eqn), axes=unknown))
    return out


# ---------------------------------------------------------------------------
# dead-fetch
# ---------------------------------------------------------------------------

_EXPENSIVE_PRIMS = frozenset({
    "dot_general", "conv_general_dilated", "scan", "while", "sort",
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "cumsum",
    "cumlogsumexp", "pjit", "custom_vjp_call_jaxpr", "custom_jvp_call",
})
_DEAD_EQN_NOISE_FLOOR = 16


@register_pass("dead-fetch", severity=Severity.WARNING,
               doc="computed-but-unfetched outputs: dead subgraphs the "
                   "fetch list forgot")
def _dead_fetch(ctx: LintContext) -> List[Diagnostic]:
    pid = "dead-fetch"
    out: List[Diagnostic] = []
    # static Program view (Executor): op outputs nobody consumes, fetches
    # or persists — the op ran for nothing
    info = ctx.program_info
    if info is not None:
        consumed = set()
        for _, ins, _ in info.get("ops", ()):
            consumed.update(ins)
        keep = (set(info.get("fetches", ())) | set(info.get("written", ()))
                | set(info.get("persistable", ())))
        for op_type, _, outs in info.get("ops", ()):
            dead = [o for o in outs
                    if o not in consumed and o not in keep]
            if dead and len(dead) == len(outs):
                out.append(_diag(
                    pid,
                    f"op '{op_type}' computes {dead} but nothing consumes "
                    f"or fetches them: add them to fetch_list or drop the "
                    f"op from the program",
                    vars=dead, op=op_type))
        return out
    if ctx.closed_jaxpr is None:
        return out
    dead = dead_eqns(ctx.closed_jaxpr)
    if not dead:
        return out
    expensive = [e for e in dead if e.primitive.name in _EXPENSIVE_PRIMS]
    if not expensive and len(dead) < _DEAD_EQN_NOISE_FLOOR:
        return out                 # a couple of dead casts are noise
    head = expensive[0] if expensive else dead[0]
    out.append(_diag(
        pid,
        f"{len(dead)} equation(s) compute values that never reach an "
        f"output ({len(expensive)} expensive, e.g. "
        f"'{head.primitive.name}'): the work is compiled and executed "
        f"every step, then thrown away — fetch the result or delete the "
        f"computation",
        user_source(head), n_dead=len(dead),
        n_expensive=len(expensive)))
    return out


# ---------------------------------------------------------------------------
# sharding-coverage
# ---------------------------------------------------------------------------

@register_pass("sharding-coverage", severity=Severity.WARNING,
               doc="param leaves no partition rule matched while the mesh "
                   "has live model-parallel axes")
def _sharding_coverage(ctx: LintContext) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    if ctx.partition_specs is None or ctx.params is None:
        return out
    mesh = ctx.mesh
    if mesh is None:
        from ..parallel.mesh import has_mesh, get_mesh
        if not has_mesh():
            return out
        mesh = get_mesh()
    from ..parallel.mesh import DP_AXIS
    live_model_axes = sorted(
        a for a, n in mesh.shape.items() if a != DP_AXIS and n > 1)
    if not live_model_axes:
        return out                  # pure-DP mesh: replicated is the rule
    pid = "sharding-coverage"
    for name in sorted(ctx.params):
        v = ctx.params[name]
        shape = tuple(getattr(v, "shape", ()))
        if len(shape) < 2 or int(np.prod(shape)) <= 1:
            continue                # scalars/vectors replicate by design
        spec = ctx.partition_specs.get(name)
        entries = tuple(spec) if spec is not None else ()
        if any(e is not None for e in entries):
            continue
        # name the autoshard rule that WOULD cover this leaf so the
        # warning is actionable (a matched pure-replication rule means
        # replication is the DECIDED layout for this role — no finding)
        rule = _autoshard_rule_for(name, shape)
        if rule is not None and not any(
                e is not None for e in tuple(rule.spec)):
            continue
        if rule is not None:
            from .autoshard import spec_repr
            hint = (f"; autoshard rule '{rule.role}' proposes "
                    f"{spec_repr(rule.spec)} — FLAGS_autoshard=apply "
                    f"closes this (=propose to review the plan first)")
        else:
            hint = ("; no autoshard rule matches — extend the "
                    "FLAGS_autoshard_rules table "
                    "(PartitionRules.with_overrides)")
        out.append(_diag(
            pid,
            f"parameter '{name}' {shape} matched no partition rule: it "
            f"replicates onto every device of the "
            f"{dict(mesh.shape)} mesh while model axes "
            f"{live_model_axes} are live — annotate it "
            f"(shard_parameter) or extend the partition rules "
            f"(match_partition_rules discipline: unmatched leaves are "
            f"a lint, not a silent default)" + hint,
            param=name, shape=shape,
            autoshard_rule=rule.role if rule is not None else None))
    return out


def _autoshard_rule_for(name, shape):
    """The active-table rule that would match one leaf (None when the
    table is unresolvable — sharding-coverage must not depend on a valid
    FLAGS_autoshard_rules value)."""
    try:
        from .autoshard import active_rules
        return active_rules().match(name, shape)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# autoshard-conflict
# ---------------------------------------------------------------------------

@register_pass("autoshard-conflict", severity=Severity.ERROR,
               doc="a hand shard_parameter annotation contradicts the "
                   "active autoshard rules table")
def _autoshard_conflict(ctx: LintContext) -> List[Diagnostic]:
    """Fires when the rules engine and a hand annotation disagree about a
    parameter's layout.  Active when the compile site carries an
    autoshard plan (TrainStep under FLAGS_autoshard != off) or when
    autoshard is enabled and the context has params to re-derive one
    from; silent otherwise, so the pass costs nothing while the
    transform is off."""
    out: List[Diagnostic] = []
    plan = (ctx.extra or {}).get("autoshard_plan")
    if plan is None:
        from .autoshard import autoshard_enabled
        if not autoshard_enabled() or ctx.params is None:
            return out
        from .autoshard import propose
        plan = propose(ctx.params, mesh=ctx.mesh,
                       existing=ctx.partition_specs,
                       sources=(ctx.extra or {}).get("autoshard_sources"))
    from .autoshard import spec_repr
    pid = "autoshard-conflict"
    for e in plan.conflicts:
        out.append(_diag(
            pid,
            f"hand annotation {spec_repr(e.existing)} on parameter "
            f"'{e.name}' {tuple(e.shape)} contradicts autoshard rule "
            f"'{e.rule}' (table {e.table}) proposing "
            f"{spec_repr(e.spec)}: the rules engine and the model "
            f"disagree about this layout — delete the shard_parameter "
            f"call, or override the rule "
            f"(PartitionRules.with_overrides) so the table owns the "
            f"decision",
            param=e.name, shape=tuple(e.shape), rule=e.rule,
            table=e.table, hand=spec_repr(e.existing),
            proposed=spec_repr(e.spec)))
    return out
