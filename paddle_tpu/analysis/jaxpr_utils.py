"""Shared jaxpr-walking machinery for the lint passes.

The closed jaxpr is the TPU analogue of the reference's ProgramDesc graph
(framework/ir/graph.h): passes here never mutate it — they only *read*
equations, so one recursive walker serves every pass.  Nested program
structure (pjit bodies, scan/while/cond branches, shard_map regions,
custom-vjp subfunctions) is flattened by :func:`iter_eqns`, which also
tracks which collective axis names each region binds — the information the
collective-consistency pass needs.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Set, Tuple

import jax


def user_source(eqn) -> Optional[str]:
    """``file.py:line (function)`` of the *user* frame that traced ``eqn``
    — jax's source_info filtered of framework/jax internals, so findings
    point at model code (operator.cc's ``Attr("op_callstack")`` analogue,
    but resolved to the outermost user frame)."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None
        return (f"{frame.file_name}:{frame.start_line}"
                f" ({frame.function_name})")
    except Exception:
        return None


def _sub_jaxprs(eqn):
    """Every jaxpr nested in an equation's params (pjit/scan/cond/
    shard_map/custom_vjp...), uniformly as open ``Jaxpr`` objects."""
    subs = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for sub in vals:
            if hasattr(sub, "eqns"):            # open Jaxpr
                subs.append(sub)
            elif hasattr(sub, "jaxpr") and hasattr(sub.jaxpr, "eqns"):
                subs.append(sub.jaxpr)          # ClosedJaxpr
    return subs


def _bound_axis_names(eqn) -> Set[str]:
    """Axis names an equation's region binds for its body: a shard_map's
    mesh axes, a pmap's axis_name."""
    out: Set[str] = set()
    mesh = eqn.params.get("mesh")
    if mesh is not None and hasattr(mesh, "axis_names"):
        out.update(str(a) for a in mesh.axis_names)
    axis_name = eqn.params.get("axis_name")
    if isinstance(axis_name, str):
        out.add(axis_name)
    elif isinstance(axis_name, (tuple, list)):
        out.update(a for a in axis_name if isinstance(a, str))
    return out


def iter_eqns(closed_jaxpr, _bound: Optional[frozenset] = None
              ) -> Iterator[Tuple[object, frozenset]]:
    """Depth-first over every equation of ``closed_jaxpr`` including nested
    jaxprs.  Yields ``(eqn, bound_axes)`` where ``bound_axes`` is the set of
    collective axis names bound by the *enclosing* regions of that eqn."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    bound = _bound or frozenset()
    for eqn in jaxpr.eqns:
        yield eqn, bound
        subs = _sub_jaxprs(eqn)
        if subs:
            inner = bound | frozenset(_bound_axis_names(eqn))
            for sub in subs:
                yield from iter_eqns(sub, inner)


def iter_jaxprs(closed_jaxpr) -> Iterator[object]:
    """Depth-first over every (open) jaxpr: the top level plus each jaxpr
    nested in equation params — for passes that need per-level dataflow
    (var producers, constvars) rather than a flat equation stream."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in _sub_jaxprs(eqn):
            yield from iter_jaxprs(sub)


def all_avals(closed_jaxpr):
    """(invars, outvars) avals of the top-level jaxpr."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    return ([v.aval for v in jaxpr.invars],
            [getattr(v, "aval", None) for v in jaxpr.outvars])


def _is_dropvar(v) -> bool:
    return type(v).__name__ == "DropVar"


def dead_eqns(closed_jaxpr) -> List[object]:
    """Equations of the TOP-LEVEL jaxpr whose outputs reach no jaxpr output
    — computed, paid for, and thrown away (the reference's graph DCE pass
    would delete them; here we *report* them, because in a fetch-driven
    Executor they usually mean a fetch list forgot an output).

    Effectful equations (callbacks, asserts) are never dead.  The analysis
    is deliberately top-level only: nested jaxprs (scan bodies etc.) are
    DCE'd by jax itself at lowering and their liveness is relative to
    their own carry."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    live = {v for v in jaxpr.outvars if not isinstance(v, jax.core.Literal)}
    # backwards sweep: an eqn is live iff any output is live (or it has
    # effects); its inputs then become live
    dead: List[object] = []
    for eqn in reversed(jaxpr.eqns):
        outs_live = any((not _is_dropvar(v)) and v in live
                        for v in eqn.outvars)
        if outs_live or getattr(eqn, "effects", None):
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    live.add(v)
        else:
            dead.append(eqn)
    dead.reverse()
    return dead


def static_vars(jaxpr) -> Set[object]:
    """Vars of ``jaxpr`` that are functions of trace-time constants only
    (constvars and literals — one forward constant-propagation sweep).
    A dynamic_slice whose start index is in this set costs nothing extra:
    XLA folds it to a static slice; only genuinely traced offsets pay the
    cross-tile gather."""
    static: Set[object] = set(getattr(jaxpr, "constvars", ()))
    for eqn in jaxpr.eqns:
        if getattr(eqn, "effects", None):
            continue
        if all(isinstance(v, jax.core.Literal) or v in static
               for v in eqn.invars):
            static.update(v for v in eqn.outvars
                          if type(v).__name__ != "DropVar")
    return static


def tile_pad_waste(dim: int, tile: int = 128) -> float:
    """Fraction of a VMEM/MXU tile wasted by padding ``dim`` up to the next
    multiple of ``tile`` (TPU minor dims tile to 128 lanes)."""
    if dim <= 0 or dim % tile == 0:
        return 0.0
    padded = ((dim + tile - 1) // tile) * tile
    return (padded - dim) / padded
