"""Flight recorder: a bounded postmortem ring every process can leave
behind.

The PR-3 numerics sentinel established the discipline — when a process
dies for a reason it can explain, it atomically writes a small JSON
artifact (``sentinel_abort.json``) instead of leaving operators to
reconstruct state from logs.  This module generalizes that to the whole
observability plane: while armed (``FLAGS_flight_dir`` /
PADDLE_TPU_FLIGHT_DIR non-empty), a background thread periodically
persists a bounded snapshot of

  * the most recent finished trace spans (``tracing.finished_spans``),
  * the recompile-ledger tail (``ledger.compile_events``),
  * the full typed-metrics registry dump + legacy monitor stats,

as ``postmortem_<id>.json`` via ``checkpoint.atomic.atomic_write_bytes``
(same-dir temp + os.replace, so the artifact is never half-written).

Three triggers, by survivability class:

  * **periodic** — every ``FLAGS_flight_interval_s``.  This is what makes
    the SIGKILL drill yield evidence from the victim: SIGKILL is
    uncatchable, but os.replace has already landed a snapshot at most one
    interval old.  A killed process cannot write; a killed process's
    last atomic write survives.
  * **sigterm** — a chained SIGTERM handler dumps before the previous
    disposition runs (cooperative shutdown leaves fresh evidence).
  * **uncaught** — a chained ``sys.excepthook`` dumps on any fatal
    uncaught exception (EnforceNotMet/FatalError included), tagging the
    artifact with the exception type.

Everything here is host-side, off the device path, and fail-open: a
recorder error must never take down the process it exists to explain.
``tools/obs_report.py --postmortem`` is the read side.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from ..framework import flags as _flags
from . import metrics as _metrics

__all__ = ["FlightRecorder", "install", "uninstall", "active", "dump"]

_lock = threading.Lock()
_rec = [None]          # the installed singleton (one artifact per process)

_DUMPS = _metrics.default_registry().counter(
    "flight_dumps_total",
    "Flight-recorder postmortem artifacts written, by trigger "
    "(periodic / sigterm / uncaught / manual / watchdog_evict).",
    labels=("reason",))


class FlightRecorder:
    """Periodic + on-signal atomic dumper of recent observability state.

    One instance owns one artifact path; ``install()`` manages the
    process-wide singleton and the signal/excepthook chaining."""

    def __init__(self, dump_dir: str, ident: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 cap: Optional[int] = None):
        self.ident = str(ident) if ident else str(os.getpid())
        self.path = os.path.join(
            dump_dir, f"postmortem_{self.ident}.json")
        self._interval = float(interval_s
                               if interval_s is not None
                               else _flags.flag("flight_interval_s"))
        self._cap = int(cap if cap is not None
                        else _flags.flag("flight_spans"))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dumps = 0
        os.makedirs(dump_dir, exist_ok=True)

    # -- snapshot ------------------------------------------------------------
    def snapshot(self, reason: str) -> dict:
        from . import ledger as _ledger
        from . import tracing as _tracing
        spans = _tracing.finished_spans()[-self._cap:]
        led = _ledger.compile_events()[-max(1, self._cap // 2):]
        return {
            "schema": "paddle_tpu/flight-recorder/1",
            "reason": reason,
            "id": self.ident,
            "pid": os.getpid(),
            "wall": time.time(),
            "monotonic": time.monotonic(),
            "argv": list(sys.argv),
            "dumps": self._dumps,
            "trace_mode": _tracing.mode(),
            "spans": spans,
            "ledger": led,
            "metrics": _metrics.default_registry().dump(
                include_stats=True),
        }

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Atomically (re)write the postmortem artifact; returns its path
        or None on failure — the recorder is fail-open by contract."""
        try:
            body = json.dumps(self.snapshot(reason), default=str)
            from ..checkpoint.atomic import atomic_write_bytes
            atomic_write_bytes(self.path, body.encode(), durable=False)
            self._dumps += 1
            _DUMPS.labels(reason).inc()
            return self.path
        except Exception:
            return None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="paddle-tpu-flight", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.dump("periodic")

    def close(self, final_dump: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_dump:
            self.dump("shutdown")


def active() -> Optional[FlightRecorder]:
    """The installed per-process recorder, or None while disarmed."""
    return _rec[0]


def dump(reason: str = "manual") -> Optional[str]:
    """Dump through the installed recorder (no-op None while disarmed) —
    the one-line hook for fatal paths (watchdog evictions, aborts)."""
    fr = _rec[0]
    return fr.dump(reason) if fr is not None else None


def install(dump_dir: Optional[str] = None, ident: Optional[str] = None,
            interval_s: Optional[float] = None,
            cap: Optional[int] = None) -> Optional[FlightRecorder]:
    """Arm the process flight recorder (idempotent): start the periodic
    dumper and chain SIGTERM + sys.excepthook triggers.  ``dump_dir``
    defaults to ``FLAGS_flight_dir``; empty means stay disarmed and
    return None — arming is always an explicit operator choice."""
    d = str(dump_dir if dump_dir is not None
            else (_flags.flag("flight_dir") or ""))
    if not d:
        return None
    with _lock:
        if _rec[0] is not None:
            return _rec[0]
        fr = FlightRecorder(d, ident=ident, interval_s=interval_s,
                            cap=cap)
        fr.start()
        fr.dump("install")          # evidence exists from second zero
        _rec[0] = fr

    prev_hook = sys.excepthook

    def _hook(tp, val, tb):
        fr.dump(f"uncaught:{getattr(tp, '__name__', tp)}")
        prev_hook(tp, val, tb)

    sys.excepthook = _hook

    try:                    # signals only wire from the main thread
        prev_term = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            fr.dump("sigterm")
            if callable(prev_term):
                prev_term(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass
    return fr


def uninstall(final_dump: bool = False) -> None:
    """Stop the periodic dumper and drop the singleton (tests).  The
    signal/excepthook chains stay in place but become no-ops through the
    closed recorder's fail-open dump."""
    with _lock:
        fr, _rec[0] = _rec[0], None
    if fr is not None:
        fr.close(final_dump=final_dump)
