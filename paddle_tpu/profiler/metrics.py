"""Typed metrics plane + latency/QPS instruments.

Reference parity: paddle/fluid/platform/monitor.h keeps int64 gauges only
(StatRegistry + the STAT_INT macro family).  Production observability
needs three typed instruments with label sets — Counter, Gauge,
Histogram — and a scrape surface.  This module layers them ON TOP of the
same registry so every existing reader keeps working:

  * :class:`MetricsRegistry` — typed metric families with label sets.
    Counter/Gauge updates (and Histogram counts) mirror into
    ``utils.monitor`` stats under a flattened name
    (``<name>[_<label-value>...]``), so ``all_stats()`` sees the typed
    plane next to the legacy gauges;
  * Prometheus text exposition (:meth:`MetricsRegistry.prometheus_text`)
    with HELP/TYPE lines and cumulative histogram buckets, served from a
    stdlib-http endpoint (:func:`serve_metrics`) or written atomically as
    a textfile (:func:`write_textfile`) for scrape-less CI;
  * the registry knows every family's (name, type, labels, owning
    module) — ``tools/gen_metrics_doc.py`` freezes that inventory into
    docs/METRICS.md the way gen_api_spec freezes signatures.

Plus the two serving instruments PR 6 introduced:

  * :class:`LatencyWindow` — a thread-safe sliding reservoir of the last
    N samples with percentile queries; ``publish(prefix)`` mirrors
    p50/p99/max into ``<prefix>_p50_us``-style integer gauges.
  * :class:`RateMeter` — completed-count over a monotonic window →
    requests/s, mirrored as ``<prefix>_qps_milli`` (int, 1/1000 qps).

Everything here is host-side and off the device hot path: an update is
one lock + a few integer adds.  All rate/duration math uses
``time.monotonic()`` — a wall-clock jump must never bend a rate.
"""
from __future__ import annotations

import os
import re
import sys
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.monitor import all_stats, stat_add, stat_set

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_registry", "serve_metrics", "write_textfile",
    "merge_histogram_payloads", "merge_dumps",
    "LatencyWindow", "RateMeter",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Prometheus-conventional latency buckets (seconds), widened at the top
# for CPU-control runs where a cold batch can take whole seconds.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _flat_stat_name(name: str, label_values: Tuple[str, ...]) -> str:
    """Flattened utils.monitor key for a labeled child: the family name
    with sanitized label VALUES appended (``train_step_phase_seconds``
    + ('host_prep',) -> ``train_step_phase_seconds_host_prep``)."""
    parts = [name] + [re.sub(r"[^a-zA-Z0-9_]", "_", str(v))
                      for v in label_values]
    return "_".join(parts)


class _Metric:
    """One metric family: fixed label names, per-label-set children."""

    kind = "untyped"

    def __init__(self, name: str, doc: str, labels: Sequence[str],
                 module: str):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for lb in labels:
            if not _LABEL_RE.match(lb):
                raise ValueError(f"invalid label name {lb!r} on {name!r}")
        self.name = name
        self.doc = " ".join(str(doc).split())      # HELP must be one line
        self.label_names = tuple(labels)
        self.module = module
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values, **kw):
        """Child for one label-value set (created on first use)."""
        if kw:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "name, not both")
            try:
                values = tuple(str(kw[k]) for k in self.label_names)
            except KeyError as e:
                raise ValueError(
                    f"metric {self.name!r} takes labels "
                    f"{self.label_names}, got {sorted(kw)}") from e
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes {len(self.label_names)} "
                f"label(s) {self.label_names}, got {len(values)}")
        with self._lock:
            ch = self._children.get(values)
            if ch is None:
                ch = self._make_child(values)
                self._children[values] = ch
            return ch

    def _default_child(self):
        if self.label_names:
            raise ValueError(
                f"metric {self.name!r} is labeled {self.label_names}: "
                "use .labels(...)")
        return self.labels()

    def _make_child(self, values):
        raise NotImplementedError

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def describe(self) -> dict:
        return {"name": self.name, "type": self.kind,
                "labels": list(self.label_names), "module": self.module,
                "doc": self.doc}


class _CounterChild:
    __slots__ = ("_lock", "_value", "_stat")

    def __init__(self, stat_name):
        self._lock = threading.Lock()
        self._value = 0.0
        self._stat = stat_name

    def inc(self, amount: float = 1.0) -> None:
        a = float(amount)
        if a < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += a
        stat_add(self._stat, int(a))

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Metric):
    """Monotonically increasing count (requests served, rows routed)."""

    kind = "counter"

    def _make_child(self, values):
        return _CounterChild(_flat_stat_name(self.name, values))

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_stat")

    def __init__(self, stat_name):
        self._lock = threading.Lock()
        self._value = 0.0
        self._stat = stat_name

    def set(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._value = v
        stat_set(self._stat, int(v))

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += float(amount)
            v = self._value
        stat_set(self._stat, int(v))

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time value that can go both ways (queue depth)."""

    kind = "gauge"

    def _make_child(self, values):
        return _GaugeChild(_flat_stat_name(self.name, values))

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count", "_stat")

    def __init__(self, bounds, stat_name):
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)       # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._stat = stat_name

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
        stat_add(self._stat)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self):
        """(cumulative bucket counts aligned to bounds+[+Inf], sum,
        count) — the exposition/quantile surface."""
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return cum, s, n

    def raw(self):
        """(raw per-bucket counts aligned to bounds+[+Inf], sum, count).

        Raw — not cumulative — counts are the mergeable form: two
        processes observing into the SAME fixed bucket layout can be
        federated by summing bucket-wise (:func:`merge_histogram_payloads`),
        which the reservoir :class:`LatencyWindow` can never support."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate in [0, 1]; None while
        empty.  Exact enough for SLO sanity ('p99 is in the right
        bucket'), not a reservoir replacement."""
        cum, _, n = self.snapshot()
        if n == 0:
            return None
        rank = q * n
        lo = 0.0
        for i, b in enumerate(self._bounds):
            if cum[i] >= rank:
                prev = cum[i - 1] if i else 0
                inb = cum[i] - prev
                frac = (rank - prev) / inb if inb else 1.0
                return lo + (b - lo) * min(1.0, max(0.0, frac))
            lo = b
        return self._bounds[-1] if self._bounds else 0.0


class Histogram(_Metric):
    """Distribution with fixed bucket boundaries (latency, batch
    occupancy).  The flattened stat mirror carries ``_count`` only —
    int gauges cannot express a distribution."""

    kind = "histogram"

    def __init__(self, name, doc, labels, module,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, doc, labels, module)
        bs = sorted(float(b) for b in buckets)
        if not bs or len(set(bs)) != len(bs):
            raise ValueError(f"invalid histogram buckets {buckets!r}")
        self.buckets = tuple(bs)

    def _make_child(self, values):
        return _HistogramChild(self.buckets,
                               _flat_stat_name(self.name, values)
                               + "_count")

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum

    def quantile(self, q: float) -> Optional[float]:
        return self._default_child().quantile(q)


def _esc_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n") \
        .replace('"', r'\"')


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class MetricsRegistry:
    """Typed metric families keyed by name.  Registration is idempotent
    for an identical (type, labels, buckets) re-declaration — module
    reloads must not fail — and loud for a conflicting one."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    # -- registration --------------------------------------------------------
    def _register(self, cls, name, doc, labels, module, **kw):
        if module is None:
            module = sys._getframe(2).f_globals.get("__name__", "?")
        with self._lock:
            prev = self._metrics.get(name)
            if prev is not None:
                same = (type(prev) is cls
                        and prev.label_names == tuple(labels)
                        and getattr(prev, "buckets", None)
                        == (tuple(sorted(float(b) for b in kw["buckets"]))
                            if "buckets" in kw else None))
                if not same:
                    raise ValueError(
                        f"metric {name!r} is already registered as "
                        f"{prev.kind}{prev.label_names}; re-registration "
                        "with a different type/labels/buckets would "
                        "silently fork the family")
                return prev
            m = cls(name, doc, labels, module, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, doc: str = "",
                labels: Sequence[str] = (),
                module: Optional[str] = None) -> Counter:
        return self._register(Counter, name, doc, labels, module)

    def gauge(self, name: str, doc: str = "",
              labels: Sequence[str] = (),
              module: Optional[str] = None) -> Gauge:
        return self._register(Gauge, name, doc, labels, module)

    def histogram(self, name: str, doc: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  module: Optional[str] = None) -> Histogram:
        return self._register(Histogram, name, doc, labels, module,
                              buckets=buckets)

    # -- introspection -------------------------------------------------------
    def collect(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def describe(self) -> List[dict]:
        """Inventory rows (name, type, labels, module, doc) — the
        docs/METRICS.md contract."""
        return [m.describe() for m in self.collect()]

    def snapshot(self) -> dict:
        """Nested value snapshot for reports: {name: {labels-repr:
        value-or-histogram-dict}}."""
        out = {}
        for m in self.collect():
            fam = {}
            for values, ch in m.children():
                key = ",".join(f"{k}={v}" for k, v in
                               zip(m.label_names, values)) or ""
                if m.kind == "histogram":
                    cum, s, n = ch.snapshot()
                    fam[key] = {"count": n, "sum": round(s, 6),
                                "p50": ch.quantile(0.5),
                                "p99": ch.quantile(0.99)}
                else:
                    fam[key] = ch.value
            out[m.name] = fam
        return out

    def dump(self, include_stats: bool = True) -> dict:
        """Portable, JSON-serializable snapshot of every family — the
        unit of cluster federation (shipped over the ``scrape`` RPC op).

        Histogram children carry RAW per-bucket counts (``raw()``), so a
        Router can bucket-sum dumps from N replicas into one cluster
        distribution; counters/gauges carry their float value.  With
        ``include_stats`` the legacy ``utils.monitor`` int gauges ride
        along under ``"stats"``."""
        fams = []
        for m in self.collect():
            fam = {"name": m.name, "kind": m.kind, "doc": m.doc,
                   "labels": list(m.label_names)}
            if m.kind == "histogram":
                fam["buckets"] = list(m.buckets)
            children = []
            for values, ch in m.children():
                if m.kind == "histogram":
                    counts, s, n = ch.raw()
                    payload = {"counts": counts, "sum": s, "count": n}
                else:
                    payload = ch.value
                children.append([list(values), payload])
            fam["children"] = children
            fams.append(fam)
        out = {"wall": time.time(), "pid": os.getpid(),
               "families": fams}
        if include_stats:
            out["stats"] = dict(all_stats())
        return out

    def _mirrored_stat_names(self) -> set:
        """Flattened utils.monitor keys owned by typed metrics (so the
        exposition's legacy-stat section never double-reports them)."""
        out = set()
        for m in self.collect():
            for values, _ in m.children():
                flat = _flat_stat_name(m.name, values)
                out.add(flat + "_count" if m.kind == "histogram"
                        else flat)
        return out

    # -- exposition ----------------------------------------------------------
    def prometheus_text(self, include_stats: bool = True) -> str:
        """Prometheus text format 0.0.4.  Typed families render with
        HELP/TYPE and cumulative histogram buckets; with
        ``include_stats`` the legacy monitor.h gauges follow as one
        ``paddle_tpu_stat{name=...}`` family (minus keys the typed plane
        already mirrors)."""
        lines: List[str] = []
        for m in self.collect():
            children = m.children()
            if not children:
                continue
            lines.append(f"# HELP {m.name} {m.doc or m.name}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for values, ch in children:
                base = ",".join(
                    f'{k}="{_esc_label(v)}"'
                    for k, v in zip(m.label_names, values))
                if m.kind == "histogram":
                    cum, s, n = ch.snapshot()
                    for b, c in zip(m.buckets, cum):
                        le = f'le="{_fmt_value(b)}"'
                        lab = f"{base},{le}" if base else le
                        lines.append(f"{m.name}_bucket{{{lab}}} {c}")
                    lab = f'{base},le="+Inf"' if base else 'le="+Inf"'
                    lines.append(f"{m.name}_bucket{{{lab}}} {cum[-1]}")
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}_sum{sfx} {_fmt_value(s)}")
                    lines.append(f"{m.name}_count{sfx} {n}")
                else:
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}{sfx} {_fmt_value(ch.value)}")
        if include_stats:
            skip = self._mirrored_stat_names()
            stats = {k: v for k, v in all_stats().items() if k not in skip}
            if stats:
                lines.append("# HELP paddle_tpu_stat monitor.h StatRegistry"
                             " int64 gauges (legacy untyped plane)")
                lines.append("# TYPE paddle_tpu_stat gauge")
                for k in sorted(stats):
                    lines.append(
                        f'paddle_tpu_stat{{name="{_esc_label(k)}"}} '
                        f"{stats[k]}")
        return "\n".join(lines) + "\n"


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem publishes into."""
    return _default


def write_textfile(path: str,
                   registry: Optional[MetricsRegistry] = None) -> str:
    """Atomically write the exposition to ``path`` (node-exporter
    textfile-collector convention — scrape-less CI reads the file)."""
    reg = registry or _default
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(reg.prometheus_text())
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Federation: merging registry dumps across processes (ISSUE 16)
# ---------------------------------------------------------------------------

def merge_histogram_payloads(payloads: Sequence[dict]) -> dict:
    """Bucket-sum merge of histogram child payloads that share one fixed
    bucket layout (``{"counts": raw per-bucket, "sum", "count"}``).

    Associative and commutative — merge order across replicas cannot
    change the cluster distribution.  Raises ValueError on a bucket-count
    mismatch rather than silently mis-binning."""
    it = iter(payloads)
    try:
        first = next(it)
    except StopIteration:
        raise ValueError("merge_histogram_payloads needs >= 1 payload")
    counts = [int(c) for c in first["counts"]]
    total_sum = float(first["sum"])
    total_count = int(first["count"])
    for p in it:
        if len(p["counts"]) != len(counts):
            raise ValueError(
                f"histogram bucket layouts disagree: {len(counts)} vs "
                f"{len(p['counts'])} buckets — refusing to mis-bin")
        counts = [a + int(b) for a, b in zip(counts, p["counts"])]
        total_sum += float(p["sum"])
        total_count += int(p["count"])
    return {"counts": counts, "sum": total_sum, "count": total_count}


def merge_dumps(dumps: Dict[str, dict]) -> Dict[str, dict]:
    """Federate per-process registry dumps (``{source_id: dump}``, each
    from :meth:`MetricsRegistry.dump`) into one cluster view:

        {family_name: {"kind", "doc", "labels", "buckets",
                       "per_source": {source: {label_values: payload}},
                       "rollup": {label_values: payload}}}

    Children with the same label values are merged across sources into
    ``rollup`` — sum for counters, bucket-sum for histograms, and
    ``{"max", "min"}`` for gauges (a cluster-summed queue depth hides the
    hot replica; max/min is the honest aggregate).  Label sets may
    overlap partially or not at all: the rollup is the union.  A family
    whose type/labels/buckets disagree across sources raises ValueError —
    federation must not silently fork a family."""
    fams: Dict[str, dict] = {}
    for src in sorted(dumps):
        for fam in dumps[src].get("families", []):
            name = fam["name"]
            buckets = tuple(fam.get("buckets", ())) or None
            f = fams.get(name)
            if f is None:
                f = {"name": name, "kind": fam["kind"],
                     "doc": fam.get("doc", ""),
                     "labels": tuple(fam["labels"]),
                     "buckets": buckets,
                     "per_source": {}, "rollup": {}}
                fams[name] = f
            elif (f["kind"] != fam["kind"]
                  or f["labels"] != tuple(fam["labels"])
                  or f["buckets"] != buckets):
                raise ValueError(
                    f"family {name!r} disagrees across sources "
                    f"({f['kind']}{f['labels']} vs "
                    f"{fam['kind']}{tuple(fam['labels'])}) — refusing "
                    "to merge forked families")
            f["per_source"][src] = {
                tuple(v): p for v, p in fam["children"]}
    for f in fams.values():
        roll: Dict[Tuple[str, ...], object] = {}
        for src in sorted(f["per_source"]):
            for values, payload in f["per_source"][src].items():
                cur = roll.get(values)
                if f["kind"] == "histogram":
                    roll[values] = (dict(payload) if cur is None else
                                    merge_histogram_payloads(
                                        [cur, payload]))
                elif f["kind"] == "counter":
                    roll[values] = float(payload) + (
                        float(cur) if cur is not None else 0.0)
                else:
                    v = float(payload)
                    if cur is None:
                        roll[values] = {"max": v, "min": v}
                    else:
                        cur["max"] = max(cur["max"], v)
                        cur["min"] = min(cur["min"], v)
        f["rollup"] = roll
    return fams


class _MetricsServer:
    """Handle for a running exposition endpoint (close() to stop)."""

    def __init__(self, httpd, thread):
        self._httpd = httpd
        self._thread = thread
        self.port = httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def serve_metrics(port: int = 0, addr: str = "127.0.0.1",
                  registry: Optional[MetricsRegistry] = None
                  ) -> _MetricsServer:
    """Serve ``GET /metrics`` (Prometheus text) from a stdlib http server
    on a daemon thread; ``port=0`` binds an ephemeral port (the handle's
    ``.port`` reports it).  No dependency beyond http.server."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    reg = registry or _default

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            body = reg.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):      # no stderr chatter per scrape
            pass

    httpd = ThreadingHTTPServer((addr, int(port)), Handler)
    t = threading.Thread(target=httpd.serve_forever,
                         name="paddle-tpu-metrics", daemon=True)
    t.start()
    return _MetricsServer(httpd, t)


# ---------------------------------------------------------------------------
# Serving instruments (PR 6)
# ---------------------------------------------------------------------------

class LatencyWindow:
    """Sliding window of the last ``maxlen`` latency samples (seconds)."""

    def __init__(self, maxlen: int = 2048):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=int(maxlen))
        self._count = 0
        self._max = 0.0

    def observe(self, seconds: float) -> None:
        s = float(seconds)
        with self._lock:
            self._buf.append(s)
            self._count += 1
            if s > self._max:
                self._max = s

    @property
    def count(self) -> int:
        """Total samples observed (not just those still in the window)."""
        with self._lock:
            return self._count

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100] over the current window; None while empty.
        Nearest-rank on the sorted window (p99 of 100 samples = the 99th)."""
        with self._lock:
            data = sorted(self._buf)
        if not data:
            return None
        if p <= 0:
            return data[0]
        if p >= 100:
            return data[-1]
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * len(data) + 0.5)) - 1))
        return data[rank]

    def snapshot(self) -> Dict[str, float]:
        """{count, p50_ms, p99_ms, max_ms} of the current window (zeros
        while empty) — the schema PERF.md's serving section records."""
        p50 = self.percentile(50)
        p99 = self.percentile(99)
        with self._lock:
            count, mx = self._count, self._max
        return {"count": count,
                "p50_ms": round((p50 or 0.0) * 1e3, 3),
                "p99_ms": round((p99 or 0.0) * 1e3, 3),
                "max_ms": round(mx * 1e3, 3)}

    def publish(self, prefix: str) -> None:
        """Mirror the window into integer gauges: ``<prefix>_p50_us``,
        ``<prefix>_p99_us``, ``<prefix>_max_us`` (microseconds)."""
        p50, p99 = self.percentile(50), self.percentile(99)
        with self._lock:
            mx = self._max
        stat_set(prefix + "_p50_us", int((p50 or 0.0) * 1e6))
        stat_set(prefix + "_p99_us", int((p99 or 0.0) * 1e6))
        stat_set(prefix + "_max_us", int(mx * 1e6))


class RateMeter:
    """Completed-count → rate (per second) since start() / last reset.

    Clocked by ``time.monotonic()``: the denominator is elapsed process
    time, so an NTP step or DST jump in the wall clock cannot spike or
    zero the reported rate."""

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._n = 0

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._n += int(n)

    def reset(self) -> None:
        with self._lock:
            self._t0 = time.monotonic()
            self._n = 0

    def rate(self) -> float:
        with self._lock:
            dt = time.monotonic() - self._t0
            n = self._n
        return n / dt if dt > 0 else 0.0

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def publish(self, prefix: str) -> None:
        """Mirror into ``<prefix>_qps_milli`` (int, qps × 1000)."""
        stat_set(prefix + "_qps_milli", int(self.rate() * 1e3))
