"""Latency/QPS instruments for long-running serving processes.

Reference parity: paddle/fluid/platform/monitor.h keeps int64 gauges only;
the serving engine needs *distributions* (p50/p99 latency) and *rates*
(QPS).  This module adds the two missing instruments on top of the same
StatRegistry so existing readers (``all_stats``) see serving health next
to the recompile ledger gauges:

  * :class:`LatencyWindow` — a thread-safe sliding reservoir of the last N
    samples with percentile queries; ``publish(prefix)`` mirrors
    p50/p99/max into ``<prefix>_p50_us``-style integer gauges.
  * :class:`RateMeter` — completed-count over a monotonic window →
    requests/s, mirrored as ``<prefix>_qps_milli`` (int, 1/1000 qps).

Host-side only and off the device hot path: one deque append per
completed request.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from ..utils.monitor import stat_set


class LatencyWindow:
    """Sliding window of the last ``maxlen`` latency samples (seconds)."""

    def __init__(self, maxlen: int = 2048):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=int(maxlen))
        self._count = 0
        self._max = 0.0

    def observe(self, seconds: float) -> None:
        s = float(seconds)
        with self._lock:
            self._buf.append(s)
            self._count += 1
            if s > self._max:
                self._max = s

    @property
    def count(self) -> int:
        """Total samples observed (not just those still in the window)."""
        with self._lock:
            return self._count

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100] over the current window; None while empty.
        Nearest-rank on the sorted window (p99 of 100 samples = the 99th)."""
        with self._lock:
            data = sorted(self._buf)
        if not data:
            return None
        if p <= 0:
            return data[0]
        if p >= 100:
            return data[-1]
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * len(data) + 0.5)) - 1))
        return data[rank]

    def snapshot(self) -> Dict[str, float]:
        """{count, p50_ms, p99_ms, max_ms} of the current window (zeros
        while empty) — the schema PERF.md's serving section records."""
        p50 = self.percentile(50)
        p99 = self.percentile(99)
        with self._lock:
            count, mx = self._count, self._max
        return {"count": count,
                "p50_ms": round((p50 or 0.0) * 1e3, 3),
                "p99_ms": round((p99 or 0.0) * 1e3, 3),
                "max_ms": round(mx * 1e3, 3)}

    def publish(self, prefix: str) -> None:
        """Mirror the window into integer gauges: ``<prefix>_p50_us``,
        ``<prefix>_p99_us``, ``<prefix>_max_us`` (microseconds)."""
        p50, p99 = self.percentile(50), self.percentile(99)
        with self._lock:
            mx = self._max
        stat_set(prefix + "_p50_us", int((p50 or 0.0) * 1e6))
        stat_set(prefix + "_p99_us", int((p99 or 0.0) * 1e6))
        stat_set(prefix + "_max_us", int(mx * 1e6))


class RateMeter:
    """Completed-count → rate (per second) since start() / last reset."""

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._n = 0

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._n += int(n)

    def reset(self) -> None:
        with self._lock:
            self._t0 = time.perf_counter()
            self._n = 0

    def rate(self) -> float:
        with self._lock:
            dt = time.perf_counter() - self._t0
            n = self._n
        return n / dt if dt > 0 else 0.0

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def publish(self, prefix: str) -> None:
        """Mirror into ``<prefix>_qps_milli`` (int, qps × 1000)."""
        stat_set(prefix + "_qps_milli", int(self.rate() * 1e3))
