"""Request-scoped span tracing: where did THIS request spend its time.

The reference runtime's platform/monitor.h + profiler stack can say how
many requests completed (int64 gauges) and where the process spends time
in aggregate (RecordEvent summary table); neither answers the production
question "why was request X slow".  This module is the Dapper-style
answer built TPU-native:

  * a **trace** is one request's tree of **spans** (trace_id/span_id/
    parent_id), covering the whole serving path — ``Server.submit`` →
    RequestQueue wait → batcher pack (with bucket/padding attribution) →
    H2D → execute → D2H → reply — plus the train-step phase breakdown
    and ``generate()``'s prefill/decode scan boundary;
  * **XLA compile events are first-class annotations**: every recompile-
    ledger record lands as an event on the active span, so a steady-state
    recompile shows up inside the exact request that paid for it;
  * **the decode scan is one device program**, so per-token span events
    are attributed at the scan boundary: the decode span carries one
    event per generated token with timestamps spread uniformly across
    the fenced scan window (the honest TPU form of per-token timing —
    the host never observes token k in isolation);
  * gating is ``FLAGS_trace`` off|sample|full (PADDLE_TPU_TRACE).  Off
    is ONE Python branch per instrumentation point (the shared
    ``enabled()`` check); sample keeps every round(1/rate)-th root span
    via a deterministic stride, so no per-request RNG draw.

Durations use ``time.monotonic()`` exclusively (a wall-clock jump — NTP
step, leap smearing — must never produce a negative or inflated span);
``time.time()`` appears only as the ``wall`` timestamp annotation.

Export is dual: :func:`export_chrome_trace` writes chrome://tracing JSON
whose timeline merges with the PR-1 profiler's host spans (one pid per
source), and a LogWriter JSONL sink (``FLAGS_trace_dir`` /
PADDLE_TPU_TRACE_DIR, size-capped rotation via FLAGS_log_writer_max_mb)
that ``tools/obs_report.py`` joins with metrics snapshots into
per-request waterfalls and SLO reports.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..framework import flags as _flags

__all__ = [
    "Span", "enabled", "mode", "should_sample", "start_span", "span",
    "child", "current_span", "use_span", "finish", "event",
    "attach_compile_event", "finished_spans", "clear",
    "enable_span_export", "disable_span_export", "drain_exported_spans",
    "set_trace_dir", "export_chrome_trace", "chrome_trace_events",
]

_lock = threading.Lock()
_ring: deque = deque(maxlen=1 << 16)      # finished span dicts, newest last
# span export (cluster trace shipping): a bounded drain-once buffer a
# replica hands to the Router's scrape poll.  None while disabled — the
# cost of the feature being off is one `is None` check inside finish().
_export_buf: Optional[deque] = None
_export_cap = 4096
_export_drops = 0
_ids = itertools.count(1)
_sample_tick = itertools.count()
_dir_override = [None]
_writer = [None, None]        # [dir the writer was opened for, LogWriter]

# ambient span for the current thread/context: children created via
# span() nest under it, and ledger compile events attach to it
_current: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_trace_span", default=None)


def mode() -> str:
    """Current FLAGS_trace value: 'off' | 'sample' | 'full'."""
    return str(_flags.flag("trace")).lower()


def enabled() -> bool:
    """One-branch gate for instrumentation points."""
    return mode() != "off"


def should_sample() -> bool:
    """Root-span sampling decision: True in full mode; every
    round(1/FLAGS_trace_sample_rate)-th call in sample mode (deterministic
    stride — converges to the rate with zero RNG cost); False when off.
    Child spans never re-sample: an unsampled root prunes its subtree by
    returning None."""
    m = mode()
    if m == "full":
        return True
    if m == "sample":
        rate = float(_flags.flag("trace_sample_rate"))
        stride = max(1, int(round(1.0 / rate)))
        return next(_sample_tick) % stride == 0
    return False


class Span:
    """One timed operation in a trace.  ``t0``/``dur`` are monotonic
    seconds (duration math survives wall-clock jumps); ``wall`` is the
    time.time() start timestamp for humans and cross-process joins."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0",
                 "wall", "dur", "attrs", "events", "_finished")

    def __init__(self, name: str, trace_id: str, span_id: int,
                 parent_id: Optional[int], t0: Optional[float] = None,
                 attrs: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = time.monotonic() if t0 is None else float(t0)
        self.wall = time.time()
        self.dur = None
        self.attrs = dict(attrs) if attrs else {}
        self.events: List[dict] = []
        self._finished = False

    def set_attr(self, **kw) -> "Span":
        self.attrs.update(kw)
        return self

    def event(self, name: str, t: Optional[float] = None, **attrs) -> None:
        """Point-in-time annotation on this span (monotonic ``t``)."""
        ev = {"name": name, "t": time.monotonic() if t is None else t}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "t0": self.t0, "dur_ms": round((self.dur or 0.0) * 1e3, 6),
                "wall": self.wall, "attrs": dict(self.attrs),
                "events": list(self.events)}


def _new_trace_id() -> str:
    return f"{os.getpid():x}-{next(_ids):x}"


def start_span(name: str, parent: Optional[Span] = None,
               trace_id: Optional[str] = None, t0: Optional[float] = None,
               **attrs) -> Optional[Span]:
    """Open a span, or return None when tracing is off / the root was not
    sampled.  With no ``parent`` and no ``trace_id`` this is a ROOT span
    and the sampling decision is made here; with a ``parent`` the child
    rides the parent's trace (a None parent from an unsampled root means
    the caller already got None and never reaches this)."""
    if not enabled():
        return None
    if parent is not None:
        return Span(name, parent.trace_id, next(_ids), parent.span_id,
                    t0=t0, attrs=attrs)
    if trace_id is not None:
        return Span(name, trace_id, next(_ids), None, t0=t0, attrs=attrs)
    if not should_sample():
        return None
    return Span(name, _new_trace_id(), next(_ids), None, t0=t0,
                attrs=attrs)


def finish(s: Optional[Span], end: Optional[float] = None) -> None:
    """Close a span: compute its monotonic duration and emit it to the
    in-memory ring and (when FLAGS_trace_dir is set) the JSONL sink.
    Idempotent; None is accepted so call sites stay one-branch."""
    if s is None or s._finished:
        return
    s._finished = True
    s.dur = max(0.0, (time.monotonic() if end is None else end) - s.t0)
    rec = s.to_dict()
    global _export_drops
    with _lock:
        _ring.append(rec)
        if _export_buf is not None:
            if len(_export_buf) >= _export_cap:
                _export_buf.popleft()
                _export_drops += 1
            _export_buf.append(rec)
        w = _get_writer()
    if w is not None:
        w.add_event("trace/span", rec)


def child(parent: Optional[Span], name: str, t0: float, t1: float,
          **attrs) -> Optional[Span]:
    """Create AND finish a child span from explicit monotonic stamps —
    the cross-thread form (queue wait, batch phases) where the timing was
    observed outside the span's own context manager."""
    if parent is None:
        return None
    s = start_span(name, parent=parent, t0=t0, **attrs)
    finish(s, end=t1)
    return s


@contextlib.contextmanager
def span(name: str, parent: Optional[Span] = None, **attrs):
    """Context-managed span nested under ``parent`` (default: the ambient
    current span, which it becomes for the duration).  Yields None when
    tracing is off or nothing upstream was sampled — call sites need no
    second branch."""
    if not enabled():
        yield None
        return
    p = parent if parent is not None else _current.get()
    s = start_span(name, parent=p, **attrs)
    if s is None:
        yield None
        return
    tok = _current.set(s)
    try:
        yield s
    finally:
        _current.reset(tok)
        finish(s)


def current_span() -> Optional[Span]:
    return _current.get()


@contextlib.contextmanager
def use_span(s: Optional[Span]):
    """Make ``s`` the ambient span WITHOUT owning its lifetime (the
    serving worker sets a request's root while executing its batch so
    ledger compile events attach to the right trace)."""
    if s is None:
        yield None
        return
    tok = _current.set(s)
    try:
        yield s
    finally:
        _current.reset(tok)


def event(name: str, **attrs) -> None:
    """Annotate the ambient span (no-op without one)."""
    s = _current.get()
    if s is not None:
        s.event(name, **attrs)


def attach_compile_event(ev: dict) -> None:
    """Recompile-ledger hook: pin a compile event to the active span so
    'why was this request slow' can answer 'an XLA compile ran inside
    it'.  One branch when no span is ambient."""
    s = _current.get()
    if s is None:
        return
    s.event("compile", site=ev.get("site"), kind=ev.get("kind"),
            ms=ev.get("ms"))


# -- sinks + export ----------------------------------------------------------

def set_trace_dir(path: Optional[str]) -> None:
    """Route finished spans to JSONL under ``path`` (None reverts to the
    ``trace_dir`` flag / env)."""
    with _lock:
        _dir_override[0] = path


def _get_writer():
    """Lazily (re)open the JSONL writer; call with _lock held."""
    d = _dir_override[0]
    if d is None:
        d = _flags.flag("trace_dir") or None
    if d != _writer[0]:
        if _writer[1] is not None:
            try:
                _writer[1].close()
            except Exception:
                pass
        from ..utils.monitor import LogWriter
        _writer[0] = d
        _writer[1] = LogWriter(logdir=d, filename_suffix=".trace") \
            if d else None
    return _writer[1]


def finished_spans(trace_id: Optional[str] = None) -> List[dict]:
    """Snapshot of the finished-span ring, oldest first."""
    with _lock:
        out = list(_ring)
    if trace_id is None:
        return out
    return [s for s in out if s["trace_id"] == trace_id]


def clear() -> None:
    """Drop ring state (tests)."""
    global _export_drops
    with _lock:
        _ring.clear()
        if _export_buf is not None:
            _export_buf.clear()
        _export_drops = 0


def enable_span_export(cap: int = 4096) -> None:
    """Start buffering finished spans for cross-process shipping.  The
    buffer is BOUNDED: past ``cap`` undrained spans the oldest are
    dropped and counted (``drain_exported_spans`` reports the running
    drop total) — a dead Router must never grow replica memory."""
    global _export_buf, _export_cap
    with _lock:
        _export_cap = max(1, int(cap))
        if _export_buf is None:
            _export_buf = deque()


def disable_span_export() -> None:
    global _export_buf, _export_drops
    with _lock:
        _export_buf = None
        _export_drops = 0


def drain_exported_spans(limit: Optional[int] = None):
    """Drain-once read of the export buffer -> (span dicts oldest first,
    cumulative drop count).  Each span is returned exactly once; drops
    are cumulative so the reader can publish a monotonic counter."""
    with _lock:
        if _export_buf is None:
            return [], _export_drops
        n = len(_export_buf) if limit is None \
            else min(int(limit), len(_export_buf))
        out = [_export_buf.popleft() for _ in range(n)]
        return out, _export_drops


def chrome_trace_events() -> List[dict]:
    """Finished spans as chrome://tracing complete events.  Timestamps
    are mapped onto the PR-1 profiler's perf_counter timeline (one
    offset sample — µs-accurate) so one merged JSON shows host
    RecordEvent spans (pid 0) and request traces (pid 1, one tid per
    trace) side by side."""
    off_us = time.perf_counter_ns() / 1e3 - time.monotonic() * 1e6
    out = []
    tids: Dict[str, int] = {}
    for s in finished_spans():
        tid = tids.setdefault(s["trace_id"], len(tids) + 1)
        ev = {"name": s["name"], "ph": "X",
              "ts": s["t0"] * 1e6 + off_us, "dur": s["dur_ms"] * 1e3,
              "pid": 1, "tid": tid, "cat": "trace",
              "args": {"trace_id": s["trace_id"], **s["attrs"]}}
        out.append(ev)
        for e in s["events"]:
            out.append({"name": f"{s['name']}::{e['name']}", "ph": "i",
                        "ts": e["t"] * 1e6 + off_us, "pid": 1,
                        "tid": tid, "s": "t", "cat": "trace",
                        "args": {k: v for k, v in e.items()
                                 if k not in ("name", "t")}})
    return out


def export_chrome_trace(path: str, include_profiler: bool = True) -> str:
    """Write finished spans (and, by default, the profiler's host
    RecordEvent buffer) as one chrome://tracing JSON file."""
    events = chrome_trace_events()
    if include_profiler:
        from . import _events as _prof_events
        events += [{"name": name, "ph": "X", "ts": t0 / 1000,
                    "dur": dur / 1000, "pid": 0, "tid": 0, "cat": "host"}
                   for name, t0, dur in _prof_events()]
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path
