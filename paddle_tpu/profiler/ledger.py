"""Recompile ledger — the "why did this recompile" answer.

Every XLA compile the framework triggers (a @to_static input-signature
miss, a static Executor program-cache miss, a TrainStep retrace on new
input shapes) is recorded with its wall time, its cache key, and a
structured diff against the previous key at the same site — the diff is
the answer to "why did this recompile": which argument changed shape,
which program version bumped, which feed dtype flipped.

Surfaced three ways:
  * StatRegistry gauges (monitor.h parity): ``jit_compile_count``,
    ``jit_cache_hit``, ``jit_compile_ms_total``.
  * an in-memory ring queryable via :func:`compile_events` (bounded, so
    a long-serving process never grows).
  * structured JSONL through ``utils.monitor.LogWriter`` when a ledger
    dir is configured (:func:`set_ledger_dir`, flag ``jit_ledger_dir``,
    env ``PADDLE_TPU_JIT_LEDGER_DIR``).

Always on: compiles are rare and cache-hit accounting is one locked
integer add, so nothing here is gated on FLAGS_enable_profiler.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..framework import flags as _flags
from ..utils.monitor import stat_add

_lock = threading.Lock()
_ring: deque = deque(maxlen=512)
_last_key: dict = {}
_dir_override = [None]
_writer = [None, None]          # [dir the writer was opened for, LogWriter]


def set_ledger_dir(path: Optional[str]) -> None:
    """Route ledger events to JSONL under ``path`` (None reverts to the
    ``jit_ledger_dir`` flag / env)."""
    with _lock:
        _dir_override[0] = path


def _get_writer():
    """Lazily (re)open the JSONL writer for the configured dir; must be
    called with _lock held."""
    d = _dir_override[0]
    if d is None:
        d = _flags.flag("jit_ledger_dir") or None
    if d != _writer[0]:
        if _writer[1] is not None:
            try:
                _writer[1].close()
            except Exception:
                pass
        from ..utils.monitor import LogWriter
        _writer[0] = d
        _writer[1] = LogWriter(logdir=d, filename_suffix=".ledger") \
            if d else None
    return _writer[1]


def _leaves(key, path=""):
    """Flatten a nested cache key into (path, repr) leaves so the diff
    points at the exact entry that changed.

    Self-describing entries — tuples whose first element is an
    ``"arg:<path>"`` label (the TrainStep/jit signature convention) —
    flatten to ONE leaf under that label, so the diff reads
    ``inputs[0]: ((8,16),'float32','weak') -> ...`` instead of a bare
    positional ``[0][3]``: the ledger and the graph-lint recompile-hazard
    pass then name the same culprit argument."""
    if isinstance(key, (tuple, list)) and key \
            and isinstance(key[0], str) and key[0].startswith("arg:"):
        label = key[0][4:]
        yield (f"{path}.{label}" if path else label, repr(tuple(key[1:])))
        return
    if isinstance(key, (tuple, list)) and any(
            isinstance(e, (tuple, list, dict)) for e in key):
        for i, e in enumerate(key):
            yield from _leaves(e, f"{path}[{i}]")
        return
    yield (path or "·", repr(key))


def key_diff(prev, cur):
    """Human-readable diff between two cache keys (the recompile cause)."""
    if prev is None:
        return ["first compile at this site"]
    p, c = dict(_leaves(prev)), dict(_leaves(cur))
    out = []
    for k in sorted(set(p) | set(c)):
        pv, cv = p.get(k, "<absent>"), c.get(k, "<absent>")
        if pv != cv:
            out.append(f"{k}: {pv} -> {cv}")
    return out or ["key unchanged (cache entry evicted or fetch-union grew)"]


def record_compile(site: str, kind: str, key, ms: float, extra=None) -> dict:
    """Record one compile event. ``site`` identifies the compile cache
    (e.g. ``jit:train_step.<locals>.f``); ``kind`` is jit / executor /
    train_step / serving_aot / generate_* / hlo_audit — or
    ``cache_load`` when the persistent executable cache
    (jit/persistent_cache.py) satisfied the site without a fresh XLA
    compile (``extra.orig_kind`` keeps the avoided kind); ``key`` the
    cache key; ``ms`` the wall time of trace+compile (first dispatch),
    or of verify+deserialize for a load."""
    with _lock:
        prev = _last_key.get(site)
        _last_key[site] = key
        ev = {"site": site, "kind": kind, "ms": round(float(ms), 3),
              "key": repr(key), "diff": key_diff(prev, key),
              "wall": time.time()}
        if extra:
            ev.update(extra)
        _ring.append(ev)
        w = _get_writer()
    stat_add("jit_compile_count")
    stat_add("jit_compile_ms_total", int(round(ms)))
    if w is not None:
        w.add_event("jit/compile", ev)
    # first-class trace annotation: a compile that runs inside a traced
    # request/step pins itself to that span (one branch when no span)
    from .tracing import attach_compile_event
    attach_compile_event(ev)
    return ev


def record_cache_hit(site: str) -> None:
    stat_add("jit_cache_hit")


def last_key(site: str):
    """The most recent cache key recorded at ``site`` (None before the
    first compile there) — the graph-lint recompile-hazard pass diffs the
    incoming key against this so the lint and the ledger's own diff name
    the same culprit."""
    with _lock:
        return _last_key.get(site)


def compile_events(site: Optional[str] = None):
    """Snapshot of recorded compile events, newest last."""
    with _lock:
        evs = list(_ring)
    if site is None:
        return evs
    return [e for e in evs if e["site"] == site]


def clear() -> None:
    """Drop recorded events and per-site key memory (tests)."""
    with _lock:
        _ring.clear()
        _last_key.clear()
