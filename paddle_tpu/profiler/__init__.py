"""paddle.profiler: tracing/profiling.

Reference parity: platform/profiler.h (RecordEvent :127,
Enable/DisableProfiler :209,:212, chrome-trace dump via profiler.proto) and
Python fluid/profiler.py:255; GPU-side CUPTI DeviceTracer (device_tracer.h:43);
the 2.x ``paddle.profiler.Profiler`` scheduler
(CLOSED/READY/RECORD/RECORD_AND_RETURN phases, ``make_scheduler``,
``on_trace_ready`` handlers, ``export_chrome_tracing``).

TPU-first: device-side timing comes from jax.profiler (XPlane → TensorBoard /
Perfetto — the CUPTI analogue is built into PJRT), activated per record
window; host-side RecordEvent spans are a lightweight aggregator with the
reference's summary table, and export_chrome_tracing writes the standard
chrome://tracing JSON.  The runtime's hot paths (static Executor, @to_static
dispatch, TrainStep, device.synchronize) are instrumented with ``span(...)``
— a shared no-op unless a Profiler window is recording or
FLAGS_enable_profiler / PADDLE_TPU_PROFILE is set, so the off-path cost is
one branch.  Recompile accounting lives in ``profiler.ledger`` and is
always on.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict, deque
from typing import Optional

import jax

from ..framework import flags as _flags

_state = threading.local()

# active record windows (Profiler phases / start_profiler sessions); spans
# are collected iff this is non-zero or FLAGS_enable_profiler is set
_active = [0]


def _events():
    if not hasattr(_state, "events"):
        # bounded: a flag-enabled long run without a scheduler must not
        # grow host memory without bound (windows managed by a Profiler
        # are cleared at every window start anyway)
        _state.events = deque(maxlen=1 << 20)
        _state.stack = []
    return _state.events


def profiling_enabled() -> bool:
    """One-branch gate for the instrumented runtime paths."""
    return _active[0] > 0 or bool(_flags.flag("enable_profiler"))


class RecordEvent:
    """platform/profiler.h:127 parity (context manager / begin-end)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()

    def end(self):
        if self._t0 is not None:
            _events().append((self.name, self._t0,
                              time.perf_counter_ns() - self._t0))
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


class _NullSpan:
    """Shared no-op stand-in returned by span() when profiling is off."""
    __slots__ = ()

    def begin(self):
        pass

    def end(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(name):
    """Gated RecordEvent for runtime instrumentation points: a real span
    while profiling is enabled, the shared no-op otherwise."""
    return RecordEvent(name) if profiling_enabled() else _NULL_SPAN


class ProfilerTarget:
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


_REC_STATES = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """paddle.profiler.make_scheduler parity: step -> ProfilerState.

    Phases cycle ``[closed (wait) | ready (warmup) | record (active)]``;
    the last record step of each cycle returns RECORD_AND_RETURN (the
    window is finalized and on_trace_ready fires there); the first
    ``skip_first`` steps are CLOSED; ``repeat=0`` cycles forever,
    ``repeat=k`` goes CLOSED after k windows."""
    if record < 1:
        raise ValueError("record span must be >= 1")
    if closed < 0 or ready < 0 or skip_first < 0 or repeat < 0:
        raise ValueError("scheduler phase lengths must be non-negative")
    span_len = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s // span_len >= repeat:
            return ProfilerState.CLOSED
        pos = s % span_len
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == span_len - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def _range_scheduler(start, stop):
    """paddle's tuple scheduler: record in [start, stop)."""
    def scheduler(step):
        if start <= step < stop:
            return (ProfilerState.RECORD_AND_RETURN if step == stop - 1
                    else ProfilerState.RECORD)
        return ProfilerState.CLOSED
    return scheduler


class Profiler:
    """paddle.profiler.Profiler parity with real scheduler semantics.

    ``scheduler`` is a callable step->ProfilerState (see make_scheduler),
    a (start, stop) tuple recording in [start, stop), or None (record
    every step from start() to stop()).  ``on_trace_ready`` receives the
    profiler at the end of every record window.  While a window records,
    host spans collect (profiling_enabled() is true) and — unless
    ``timer_only`` — jax.profiler captures device-side XPlane data into
    ``profiler_result_dir``.
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        if scheduler is None:
            self._scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, (tuple, list)):
            self._scheduler = _range_scheduler(int(scheduler[0]),
                                               int(scheduler[1]))
        else:
            self._scheduler = scheduler
        self._dir = None
        self._on_ready = on_trace_ready
        self._timer_only = timer_only
        self._jax_started = False
        self._step = 0
        self.current_state = ProfilerState.CLOSED
        self._recording = False
        self._step_t0 = None
        self.round_count = 0          # completed record windows

    # -- window management ---------------------------------------------------
    def _begin_window(self):
        _events().clear()
        _active[0] += 1
        self._recording = True
        self._step_t0 = time.perf_counter_ns()
        if not self._timer_only:
            import tempfile
            self._dir = tempfile.mkdtemp(prefix="paddle_tpu_prof_")
            try:
                jax.profiler.start_trace(self._dir)
                self._jax_started = True
            except Exception:
                self._jax_started = False

    def _end_window(self):
        # fence pending device work so the window's device trace and the
        # final step span are honest (on a tunneled TPU only a D2H fetch
        # truly fences; device.synchronize is the framework's fence)
        try:
            from .. import device as _device
            _device.synchronize()
        except Exception:
            pass
        if self._jax_started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_started = False
        self._recording = False
        _active[0] = max(0, _active[0] - 1)
        self.round_count += 1
        if self._on_ready is not None:
            self._on_ready(self)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self._step = 0
        self.current_state = self._scheduler(0)
        if self.current_state in _REC_STATES:
            self._begin_window()

    def step(self, num_samples=None):
        prev = self.current_state
        if self._recording:
            now = time.perf_counter_ns()
            _events().append((f"ProfileStep#{self._step}", self._step_t0,
                              now - self._step_t0))
            self._step_t0 = now
        self._step += 1
        self.current_state = self._scheduler(self._step)
        if self._recording and (prev == ProfilerState.RECORD_AND_RETURN
                                or self.current_state not in _REC_STATES):
            self._end_window()
        if not self._recording and self.current_state in _REC_STATES:
            self._begin_window()

    def stop(self):
        if self._recording:
            now = time.perf_counter_ns()
            _events().append((f"ProfileStep#{self._step}", self._step_t0,
                              now - self._step_t0))
            self._end_window()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        s = summary_string()
        print(s)
        return s

    @property
    def profiler_result_dir(self):
        return self._dir


def summary_string():
    """Event summary table (profiler.cc report parity: calls/total/avg/max)."""
    agg = defaultdict(lambda: [0, 0, 0])  # name -> [calls, total_ns, max_ns]
    for name, _, dur in _events():
        a = agg[name]
        a[0] += 1
        a[1] += dur
        a[2] = max(a[2], dur)
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"
             f"{'Max(ms)':>12}", "-" * 84]
    for name, (calls, total, mx) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40}{calls:>8}{total / 1e6:>12.3f}"
                     f"{total / calls / 1e6:>12.3f}{mx / 1e6:>12.3f}")
    return "\n".join(lines)


def export_chrome_tracing(dir_name, worker_name=None):
    """Write host events as chrome://tracing JSON (profiler.proto dump
    parity); returns an on_trace_ready callback.  With a worker_name,
    repeat windows write one file per round; the default filename keeps
    the historical ``paddle_tpu_trace.json`` (overwritten per window)."""
    import os

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        trace = [{"name": name, "ph": "X", "ts": t0 / 1000,
                  "dur": dur / 1000, "pid": 0, "tid": 0, "cat": "host"}
                 for name, t0, dur in _events()]
        # merge finished request spans (profiler.tracing) into the same
        # timeline: pid 0 = host RecordEvents, pid 1 = request traces
        from .tracing import chrome_trace_events
        trace += chrome_trace_events()
        if worker_name:
            rnd = getattr(prof, "round_count", 0) or 1
            fname = f"{worker_name}_r{rnd}.json"
        else:
            fname = "paddle_tpu_trace.json"
        with open(os.path.join(dir_name, fname), "w") as f:
            json.dump({"traceEvents": trace}, f)
    return handler


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """fluid.profiler.profiler (fluid/profiler.py:255) parity."""
    p = Profiler(timer_only=True)
    p.start()
    try:
        yield
    finally:
        p.stop()
        print(summary_string())


def start_profiler(state="All"):
    _events().clear()
    _active[0] += 1


def stop_profiler(sorted_key=None, profile_path=None):
    _active[0] = max(0, _active[0] - 1)
    print(summary_string())


# recompile ledger (always-on compile accounting; see ledger.py)
from . import ledger  # noqa: E402,F401
from .ledger import compile_events, set_ledger_dir  # noqa: E402,F401

# typed metrics plane + serving instruments (see metrics.py)
from . import metrics  # noqa: E402,F401
from .metrics import (Counter, Gauge, Histogram,  # noqa: E402,F401
                      LatencyWindow, MetricsRegistry, RateMeter,
                      default_registry, serve_metrics, write_textfile)

# request-scoped span tracing (FLAGS_trace; see tracing.py)
from . import tracing  # noqa: E402,F401
from .tracing import Span, export_chrome_trace, set_trace_dir  # noqa: E402,F401

# device-side: direct jax.profiler bridges
start_trace = jax.profiler.start_trace
stop_trace = jax.profiler.stop_trace
TraceAnnotation = jax.profiler.TraceAnnotation
