"""Thin marshalling layer for the C inference ABI (native/capi.cpp).

Reference parity: paddle/fluid/inference/capi/ — a C-callable surface over
the predictor so C/Go/R programs can serve a saved model. The TPU build's
predictor is Python-over-PJRT, so the C shim embeds CPython and calls the
two functions here with only (str, bytes, tuple) types — no Python API
surface leaks into the C side beyond these.
"""
from __future__ import annotations

import numpy as np

from . import Config, create_predictor


def create(model_path):
    """C: pd_predictor_create."""
    return create_predictor(Config(model_path))


def run_f32(pred, data, shape):
    """C: pd_predictor_run_f32 — one float32 input, first float32 output.
    Returns (out_bytes, out_shape_tuple)."""
    arr = np.frombuffer(data, np.float32).reshape(shape)
    outs = pred.run([arr])
    out = np.ascontiguousarray(np.asarray(outs[0], np.float32))
    return out.tobytes(), tuple(int(d) for d in out.shape)


def train_create(model_prefix, feed_names, fetch_name):
    """C: pd_trainer_create — the reference's C++ train demo
    (paddle/fluid/train/demo/demo_trainer.cc): load a TRAIN program saved
    by static.save (optimizer ops included) plus its persistables, ready
    to step without Python on the consumer side."""
    import paddle_tpu as paddle
    from paddle_tpu import static

    was_dygraph = paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        prog = static.deserialize_program(
            open(model_prefix + ".pdmodel", "rb").read())
        exe = static.Executor()
        static.load(prog, model_prefix, exe)
    finally:
        if was_dygraph:
            paddle.disable_static()
    return {"program": prog, "exe": exe,
            "feeds": [n for n in feed_names.split(",") if n],
            "fetch": fetch_name}


def train_step(trainer, x_bytes, x_shape, label_bytes, label_shape):
    """C: pd_trainer_step_f32 — one train step (fwd+bwd+update through the
    compiled replay); returns the fetched loss as a float."""
    import paddle_tpu as paddle

    x = np.frombuffer(x_bytes, np.float32).reshape(x_shape)
    label = np.frombuffer(label_bytes, np.int64).reshape(label_shape)
    feeds = dict(zip(trainer["feeds"], (x, label)))
    was_dygraph = paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        outs = trainer["exe"].run(trainer["program"], feed=feeds,
                                  fetch_list=[trainer["fetch"]])
    finally:
        if was_dygraph:
            paddle.disable_static()
    return float(np.asarray(outs[0]).reshape(-1)[0])


def set_input(pred, name, data, shape, dtype):
    """C: pd_predictor_set_input_* — stage one named feed
    (PD_SetZeroCopyInput parity)."""
    arr = np.frombuffer(data, dtype).reshape(shape)
    pred.get_input_handle(name).copy_from_cpu(arr)


def run_staged(pred):
    """C: pd_predictor_run2 — run on the staged feeds; returns the output
    count."""
    pred.run()
    return len(pred.get_output_names())


def get_output_f32(pred, idx):
    """C: pd_predictor_get_output_f32 — output #idx as float32 bytes."""
    name = pred.get_output_names()[idx]
    out = pred.get_output_handle(name).copy_to_cpu()
    out = np.ascontiguousarray(np.asarray(out, np.float32))
    return out.tobytes(), tuple(int(d) for d in out.shape)


def io_names(pred):
    """C: pd_predictor_io_names — 'in1,in2|out1,out2'."""
    return ",".join(pred.get_input_names()) + "|" + \
        ",".join(pred.get_output_names())
