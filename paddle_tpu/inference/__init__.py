"""paddle.inference: deployment API.

Reference parity: paddle/fluid/inference/api/analysis_predictor.h:82
(AnalysisPredictor with AnalysisConfig, ZeroCopyRun :165) bound to Python via
pybind/inference_api.cc.

TPU-first: "analysis + IR optimization" is the XLA pipeline — the predictor
loads a saved program (static.io format or jit.save StableHLO) and jit-caches
one executable per input signature; zero-copy IO ≙ donated device arrays.
The TensorRT/Lite subgraph engines have no TPU meaning; their slot is the
PJRT executable cache itself.
"""
from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from ..framework.tensor import Tensor


class Config:
    """AnalysisConfig parity."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file and os.path.isdir(prog_file):
            self._model_dir = prog_file
            self._prog_file = None
            self._params_file = None
        else:
            self._model_dir = None
            self._prog_file = prog_file
            self._params_file = params_file
        self._use_tpu = True
        self._memory_optim = True
        self._glog_info = False
        self._optim_cache_dir = None
        self._quant_signature = None

    def set_optim_cache_dir(self, path):
        """AnalysisConfig::SetOptimCacheDir parity: compiled PJRT
        executables persist here, so a serving restart deserializes them
        instead of recompiling (the TensorRT engine-cache slot)."""
        self._optim_cache_dir = path

    def optim_cache_dir(self):
        return self._optim_cache_dir

    def set_quant_signature(self, signature):
        """Pin the quantization signature mixed into the AOT executable
        cache key (quantization.freeze.quant_signature). Normally read
        from the model's ``.quant.json`` sidecar automatically; set it
        explicitly for hand-assembled int8 programs."""
        self._quant_signature = signature

    def quant_signature(self):
        return self._quant_signature

    def set_model(self, prog_file, params_file=None):
        cache_dir = self._optim_cache_dir
        quant_sig = self._quant_signature
        self.__init__(prog_file, params_file)
        self._optim_cache_dir = cache_dir
        self._quant_signature = quant_sig

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def enable_use_gpu(self, *a, **k):
        pass  # device choice is PJRT's

    def enable_xpu(self, *a, **k):
        pass

    def disable_glog_info(self):
        self._glog_info = False

    def enable_memory_optim(self):
        self._memory_optim = True

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes

    def switch_use_feed_fetch_ops(self, flag):
        pass


class PredictorTensor:
    """ZeroCopyTensor parity: named IO slot."""

    def __init__(self, predictor, name, is_input):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        from ..framework.enforce import InvalidArgumentError
        if not self._is_input:
            raise InvalidArgumentError(
                f"copy_from_cpu on fetch {self.name!r}: only feed handles "
                "accept input data (use copy_to_cpu to read outputs)")
        arr = np.asarray(arr)
        declared = self._p._declared_shapes.get(self.name)
        if declared is not None and tuple(arr.shape) != declared:
            # ZeroCopyTensor::Reshape contract: the declared shape is a
            # promise the next copy must keep (was a silent no-op)
            raise InvalidArgumentError(
                f"feed {self.name!r}: copy_from_cpu got shape "
                f"{list(arr.shape)} but reshape() declared "
                f"{list(declared)}")
        self._p._feeds[self.name] = arr

    def copy_to_cpu(self):
        from ..framework.enforce import NotFoundError
        if self._is_input:
            if self.name not in self._p._feeds:
                raise NotFoundError(
                    f"feed {self.name!r} has no value yet — "
                    "copy_from_cpu() it first")
            return np.asarray(self._p._feeds[self.name])
        if self.name not in self._p._results:
            raise NotFoundError(
                f"fetch {self.name!r} has no value yet — call run() "
                "before copy_to_cpu()")
        return np.asarray(self._p._results[self.name])

    def reshape(self, shape):
        """ZeroCopyTensor::Reshape parity: declare the shape the next
        copy_from_cpu must carry.  Validated, not allocated — XLA owns
        device buffers, so the declaration is a contract, and a
        mismatching copy_from_cpu raises instead of silently serving the
        wrong shape."""
        from ..framework.enforce import InvalidArgumentError
        if not self._is_input:
            raise InvalidArgumentError(
                f"reshape on fetch {self.name!r}: output shapes are "
                "decided by the compiled program")
        dims = []
        for d in shape:
            d = int(d)
            if d <= 0:
                raise InvalidArgumentError(
                    f"feed {self.name!r}: reshape dims must be concrete "
                    f"positive ints, got {list(shape)} (dynamic batch is "
                    "declared at export via InputSpec([None, ...]))")
            dims.append(d)
        self._p._declared_shapes[self.name] = tuple(dims)

    def shape(self):
        from ..framework.enforce import NotFoundError
        if self._is_input:
            declared = self._p._declared_shapes.get(self.name)
            if declared is not None:
                return list(declared)
            if self.name not in self._p._feeds:
                raise NotFoundError(
                    f"feed {self.name!r} has no shape yet — reshape() or "
                    "copy_from_cpu() it first")
            return list(self._p._feeds[self.name].shape)
        if self.name not in self._p._results:
            raise NotFoundError(
                f"fetch {self.name!r} has no shape yet — call run() "
                "before shape()")
        return list(np.asarray(self._p._results[self.name]).shape)


class Predictor:
    """AnalysisPredictor parity over the static Executor's compiled replay."""

    def __init__(self, config: Config):
        from ..framework.flags import flag
        from ..quantization.freeze import load_quant_sidecar
        from ..static.io import load_inference_model
        from ..static.executor import Executor
        d = config.model_dir() or config.prog_file()
        if d is None:
            raise ValueError("Config needs a model dir (save_inference_model"
                             " output or jit.save prefix dir)")
        self._translated = None
        self._quant_info = None
        prefix = self._jit_prefix(d)
        if prefix is not None:
            # int8 serving (FLAGS_use_int8_inference / PADDLE_TPU_INT8):
            # prefer the frozen '.int8' sibling artifact when present —
            # the off-path is this one branch
            if flag("use_int8_inference") and not prefix.endswith(".int8") \
                    and os.path.isfile(prefix + ".int8.pdmodel"):
                self._quant_info = load_quant_sidecar(prefix)
                prefix = prefix + ".int8"
            elif prefix.endswith(".int8"):
                self._quant_info = load_quant_sidecar(prefix[:-len(".int8")])
            # jit.save'd model (StableHLO + params): dynamic dims exported
            # as symbolic shapes, so any batch size runs without recompile
            from .. import jit as _jit
            self._translated = _jit.load(prefix)
            self._feed_names = [f"x{i}" for i in range(
                self._translated.num_inputs)]
            self._fetch_names = [f"out{i}" for i in range(
                self._translated.num_outputs)]
        else:
            self._program, self._feed_names, self._fetch_vars = \
                load_inference_model(d)
            self._fetch_names = [v.name for v in self._fetch_vars]
            self._exe = Executor()
            if config.optim_cache_dir():
                self._exe.set_aot_cache_dir(config.optim_cache_dir())
            # AOT executable cache keys on the quant signature so int8 and
            # float programs sharing one cache dir never collide
            sig = config.quant_signature()
            if sig is None and self._quant_info:
                sig = self._quant_info.get("signature")
            if sig is not None:
                self._exe.set_cache_extra_key(f"quant:{sig}")
        self._feeds: Dict[str, np.ndarray] = {}
        self._results: Dict[str, np.ndarray] = {}
        self._declared_shapes: Dict[str, tuple] = {}

    def quant_info(self):
        """The served model's quantization sidecar (quant.json) when the
        int8 artifact was selected; None on the float path."""
        return self._quant_info

    def clone(self):
        """AnalysisPredictor::Clone parity (analysis_predictor.h:214):
        a predictor sharing this one's WEIGHTS and compiled executables,
        with its own IO buffers — one clone per serving thread.  Weights
        are shared by construction: the clone aliases the same loaded
        program/TranslatedLayer and the same Executor (whose compiled
        replay closes over the scope's parameter buffers); device arrays
        are immutable, so concurrent run() calls race only on their own
        per-clone feed/result dicts."""
        import copy
        c = copy.copy(self)           # aliases program/executor/weights
        c._feeds = {}                 # own IO buffers per serving thread
        c._results = {}
        c._declared_shapes = {}
        return c

    @staticmethod
    def _jit_prefix(d):
        import glob
        if d.endswith(".pdmodel"):
            return d[:-len(".pdmodel")]
        if os.path.isfile(d + ".pdmodel"):
            return d
        if os.path.isfile(d + ".int8.pdmodel"):
            return d + ".int8"      # int8-only export: serve what exists
        if os.path.isdir(d) and not os.path.exists(
                os.path.join(d, "__model__")):
            # '.int8' siblings are variants of a float prefix, not models
            # of their own — the int8 branch above opts into them
            pdm = sorted(p for p in glob.glob(os.path.join(d, "*.pdmodel"))
                         if not p.endswith(".int8.pdmodel"))
            if pdm:
                return pdm[0][:-len(".pdmodel")]
            pdm = sorted(glob.glob(os.path.join(d, "*.int8.pdmodel")))
            if pdm:
                return pdm[0][:-len(".pdmodel")]
        return None

    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return PredictorTensor(self, name, True)

    def get_output_handle(self, name):
        return PredictorTensor(self, name, False)

    def run(self, inputs=None):
        """ZeroCopyRun parity; also accepts positional arrays like the 2.x
        predictor.run(list)."""
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._feeds[name] = np.asarray(
                    arr.numpy() if isinstance(arr, Tensor) else arr)
        if self._translated is not None:
            out = self._translated(
                *[self._feeds[n] for n in self._feed_names])
            outs = [np.asarray(o.numpy()) for o in
                    (out if isinstance(out, (list, tuple)) else [out])]
        else:
            outs = self._exe.run(self._program, feed=dict(self._feeds),
                                 fetch_list=self._fetch_names)
        self._results = dict(zip(self._fetch_names, outs))
        return [self._results[n] for n in self._fetch_names]

    def run_async(self, inputs=None):
        """run() without the host fence: outputs stay device-backed jax
        arrays (dispatch is asynchronous), so a serving worker can overlap
        H2D + execution of the next batch with this one — ``np.asarray``
        (or copy_to_cpu) on a result is the fence.  Results land in the
        same per-predictor buffers run() uses."""
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._feeds[name] = arr.numpy() if isinstance(arr, Tensor) \
                    else arr
        if self._translated is not None:
            out = self._translated(
                *[self._feeds[n] for n in self._feed_names])
            outs = [o._value for o in
                    (out if isinstance(out, (list, tuple)) else [out])]
        else:
            outs = [t._value for t in self._exe.run(
                self._program, feed=dict(self._feeds),
                fetch_list=self._fetch_names, return_numpy=False)]
        self._results = dict(zip(self._fetch_names, outs))
        return [self._results[n] for n in self._fetch_names]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
