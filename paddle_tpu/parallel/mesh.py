"""Global device mesh: the TPU equivalent of NCCL ring/communicator state.

Reference parity: ``NCCLCommContext`` keeps a ring_id -> communicator map
(paddle/fluid/platform/collective_helper.h:50,63) bootstrapped by TCP
rendezvous of ncclUniqueId (operators/collective/c_gen_nccl_id_op).  On TPU
none of that exists: topology is discovered by PJRT at init, and "rings" are
named axes of a ``jax.sharding.Mesh``.  A process-global mesh is installed
once (init_mesh) and every parallel strategy is expressed as a PartitionSpec
over its axes:

  dp — data parallel (batch dim; grad all-reduce rides ICI)
  mp — model/tensor parallel (Megatron-style split of weight matrices)
  pp — pipeline parallel (layer stages)
  sp — sequence/context parallel (long-sequence sharding; absent in the
       reference — see SURVEY.md §5 'Long-context' — but first-class here)
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

DP_AXIS = "dp"
MP_AXIS = "mp"
PP_AXIS = "pp"
SP_AXIS = "sp"
# expert parallel (Mixture-of-Experts): expert stacks shard over it,
# token rows all_to_all across it (nn/layer/moe.py; absent in the
# reference — its MoE seat is the parameter-server sparse table)
EP_AXIS = "ep"

_AXIS_ORDER = (DP_AXIS, EP_AXIS, PP_AXIS, MP_AXIS, SP_AXIS)

_current_mesh: Optional[Mesh] = None


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from {axis_name: size}. Sizes must multiply to ndevices
    (a size of -1 is inferred). Axis order follows dp, pp, mp, sp so that the
    innermost (fastest-varying, best-ICI-locality) axis is mp/sp — the axes
    with the most latency-sensitive collectives."""
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    axes = {k: v for k, v in axes.items() if v != 1 or k == DP_AXIS}
    if not axes:
        axes = {DP_AXIS: n}
    names = [a for a in _AXIS_ORDER if a in axes] + \
            [a for a in axes if a not in _AXIS_ORDER]
    sizes = [axes[a] for a in names]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} need {total} "
                         f"devices, have {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def init_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Install the process-global mesh (c_comm_init_all analogue,
    operators/collective/c_comm_init_all_op.cc). Defaults to pure DP over all
    visible devices."""
    global _current_mesh
    _current_mesh = make_mesh(axes or {DP_AXIS: -1}, devices)
    return _current_mesh


def get_mesh() -> Mesh:
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = make_mesh({DP_AXIS: len(jax.devices())})
    return _current_mesh


def has_mesh() -> bool:
    return _current_mesh is not None


def mesh_axis_size(axis: str) -> int:
    mesh = get_mesh()
    return mesh.shape.get(axis, 1)


@contextlib.contextmanager
def MeshGuard(mesh: Mesh):
    """Temporarily swap the global mesh (tests, nested strategies)."""
    global _current_mesh
    prev = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = prev
