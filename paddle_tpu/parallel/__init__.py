"""TPU-native SPMD parallel engine.

This package is the TPU replacement for the reference's entire multi-device
machinery: ParallelExecutor's SSA graphs (paddle/fluid/framework/
parallel_executor.cc:613), the NCCL comm registry (platform/
collective_helper.h:50) and fleet's program-rewriting meta-optimizers
(python/paddle/distributed/fleet/meta_optimizers/). Instead of rewriting op
graphs to insert collectives, the engine:

  1. declares a global ``jax.sharding.Mesh`` with named axes
     (dp/mp/pp/sp — data, model/tensor, pipeline, sequence),
  2. annotates parameters and batches with ``PartitionSpec``s,
  3. jit-compiles the WHOLE train step once; XLA GSPMD partitions it and
     inserts all-reduce/all-gather/reduce-scatter on ICI automatically.

The user-facing paddle-compatible API (paddle.distributed.*, fleet) in
``paddle_tpu/distributed/`` is a facade over this engine.
"""
from .mesh import (  # noqa: F401
    init_mesh, get_mesh, has_mesh, mesh_axis_size, MeshGuard, make_mesh,
    DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS,
)
from .api import (  # noqa: F401
    shard_parameter, get_partition_spec, annotation_source,
    named_shardings, batch_sharding, replicated_sharding, shard_tensor,
)
from .train_step import TrainStep, EvalStep  # noqa: F401
from .pipeline import GPipe, PipelineModule  # noqa: F401
from .sp import ring_attention  # noqa: F401
