"""Pipeline parallelism: GPipe over the ``pp`` mesh axis.

Reference parity: PipelineOptimizer (python/paddle/fluid/optimizer.py:3702)
splits the program into per-device section programs by device_guard and
inserts send_v2/recv_v2 at boundaries (:4178); C++ PipelineTrainer +
SectionWorker run the GPipe schedule — all-forward over microbatches
(section_worker.cc:61), all-backward (:87), then update (:106).

TPU-first: the pipeline is ONE SPMD program.  Stages are shards of the
``pp`` mesh axis; the per-stage weights are the same pytree stacked along a
leading [S, ...] dim sharded P('pp'); microbatch activations flow between
stages with lax.ppermute inside a lax.scan over schedule ticks.  The
backward schedule is not hand-written (no section_worker backward loop):
jax.grad differentiates through scan+ppermute and emits the reverse
pipeline automatically, and XLA overlaps the permutes with compute.
"""
from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import functional as F
from ..framework.tensor import Tensor
from .mesh import get_mesh, PP_AXIS, DP_AXIS


def pipeline_spmd(stage_fn: Callable, num_stages: int, num_microbatches: int):
    """Build the per-shard GPipe body (call inside shard_map with axis pp).

    stage_fn(stage_params, x) -> y applies ONE stage's layers.
    Input x_mb: [M, mb, ...] microbatched activations (same on every stage;
    only stage 0's injection is used).  Returns [M, mb, ...] outputs valid on
    the LAST stage (other stages hold garbage — callers psum-select).
    """
    S, M = num_stages, num_microbatches

    def run(stage_params, x_mb):
        idx = lax.axis_index(PP_AXIS)
        # carry becomes pp-varying after the first ppermute; mark the initial
        # zeros as varying over pp so scan's carry types line up (VMA rule)
        zero = lax.pvary(jnp.zeros_like(x_mb[0]), (PP_AXIS,))

        def tick(carry, t):
            incoming = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            inject = x_mb[mb_idx]
            act_in = jnp.where(idx == 0, inject, incoming)
            out = stage_fn(stage_params, act_in)
            shifted = lax.ppermute(
                out, PP_AXIS, [(i, (i + 1) % S) for i in range(S)])
            return shifted, out

        _, outs = lax.scan(tick, zero, jnp.arange(M + S - 1))
        # last stage emits microbatch m at tick m + S - 1
        final = outs[S - 1:]
        # broadcast the last stage's result to every stage so downstream
        # (loss) code is stage-agnostic: mask + psum
        mine = jnp.where(idx == S - 1, final, jnp.zeros_like(final))
        return lax.psum(mine, PP_AXIS)

    return run


class GPipe:
    """Pipeline a homogeneous stack of blocks (e.g. transformer layers).

    ≙ PipelineOptimizer + PipelineTrainer as one object. Blocks must share
    structure (same param pytree); layers are grouped into ``num_stages``
    stages of equal depth. Embedding/head layers stay replicated outside the
    pipelined trunk.
    """

    def __init__(self, blocks: List, num_stages: int = None, mesh=None,
                 num_microbatches: int = 2):
        self.mesh = mesh or get_mesh()
        self.S = num_stages or self.mesh.shape.get(PP_AXIS, 1)
        assert len(blocks) % self.S == 0, \
            f"{len(blocks)} blocks not divisible by {self.S} stages"
        self.blocks = blocks
        self.M = num_microbatches
        self.per_stage = len(blocks) // self.S

        # stack params: [n_blocks, ...] -> grouped [S, per_stage, ...]
        names = None
        all_params = []
        for b in blocks:
            p, _ = F.layer_state(b)
            if names is None:
                names = list(p)
            all_params.append([p[n] for n in names])
        self.param_names = names
        self.stacked = {
            n: jnp.stack([all_params[i][j] for i in range(len(blocks))])
                 .reshape((self.S, self.per_stage)
                          + all_params[0][j].shape)
            for j, n in enumerate(names)}
        # shard leading stage dim over pp
        self.stacked = {
            n: jax.device_put(v, NamedSharding(
                self.mesh, P(PP_AXIS) if self.mesh.shape.get(PP_AXIS, 1) > 1
                else P()))
            for n, v in self.stacked.items()}

    def _stage_fn(self):
        block0 = self.blocks[0]
        names = self.param_names
        per_stage = self.per_stage

        def apply_block(x, block_params):
            params = dict(zip(names, block_params))
            return F.functional_call(block0, params, None, (x,),
                                     training=False)

        def stage(stage_params, x):
            # inside shard_map the leading [S] dim is sliced to [1]:
            # stage_params[n]: [1, per_stage, ...]
            def body(x, i):
                bp = [stage_params[n][0, i] for n in names]
                return apply_block(x, bp), None
            out, _ = lax.scan(body, x, jnp.arange(per_stage))
            return out

        return stage

    def build_forward(self):
        """Return pure fn(stacked_params, x [B, ...]) -> y executed as SPMD
        over the pp (and dp) axes of the mesh."""
        from jax import shard_map
        S, M = self.S, self.M
        body = pipeline_spmd(self._stage_fn(), S, M)
        mesh = self.mesh
        dp = mesh.shape.get(DP_AXIS, 1)

        param_specs = {n: P(PP_AXIS) for n in self.param_names}

        def fwd(stacked, x):
            mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            # shard the per-microbatch batch dim over dp only when divisible
            bshard = DP_AXIS if (dp > 1 and mb.shape[1] % dp == 0) else None
            data_spec = P(None, bshard)
            out_mb = shard_map(
                body, mesh=mesh,
                in_specs=(param_specs, data_spec),
                out_specs=data_spec,
            )({n: stacked[n] for n in self.param_names}, mb)
            return out_mb.reshape((-1,) + out_mb.shape[2:])

        return fwd

    def __call__(self, x):
        fwd = self.build_forward()
        arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(fwd(self.stacked, arr))
