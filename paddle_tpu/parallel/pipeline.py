"""Pipeline parallelism: GPipe over the ``pp`` mesh axis.

Reference parity: PipelineOptimizer (python/paddle/fluid/optimizer.py:3702)
splits the program into per-device section programs by device_guard and
inserts send_v2/recv_v2 at boundaries (:4178); C++ PipelineTrainer +
SectionWorker run the GPipe schedule — all-forward over microbatches
(section_worker.cc:61), all-backward (:87), then update (:106).

TPU-first: the pipeline is ONE SPMD program.  Stages are shards of the
``pp`` mesh axis; the per-stage weights are the same pytree stacked along a
leading [S, ...] dim sharded P('pp'); microbatch activations flow between
stages with lax.ppermute inside a lax.scan over schedule ticks.  The
backward schedule is not hand-written (no section_worker backward loop):
jax.grad differentiates through scan+ppermute and emits the reverse
pipeline automatically, and XLA overlaps the permutes with compute.
"""
from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import functional as F
from ..framework.tensor import Tensor
from .mesh import get_mesh, PP_AXIS, DP_AXIS

# lax.pvary arrived with the varying-manual-axes rep rule (~jax 0.6); on
# older jax shard_map has no VMA typing and the marker is a no-op
_pvary = getattr(lax, "pvary", lambda x, axes: x)


def pipeline_spmd_train(stage_fn: Callable, num_stages: int,
                        num_microbatches: int):
    """GPipe schedule body (call inside shard_map with axis pp).

    ``stage_fn(stage_params, x, key)`` applies ONE stage's layers; the PRNG
    key is folded per schedule tick and stage so every microbatch/stage pass
    draws distinct randomness (dropout).  ``key_data`` is the uint32 key
    data (shard_map-friendly); pass ``jax.random.key_data(key)``.

    Input x_mb: [M, mb, ...] microbatched activations (same on every stage;
    only stage 0's injection is used).  Returns [M, mb, ...] outputs valid
    on every stage (the last stage's result is psum-broadcast so downstream
    loss code is stage-agnostic).
    """
    S, M = num_stages, num_microbatches

    def run(stage_params, x_mb, key_data):
        idx = lax.axis_index(PP_AXIS)
        base = jax.random.wrap_key_data(key_data)
        # carry becomes pp-varying after the first ppermute; mark the initial
        # zeros as varying over pp so scan's carry types line up (VMA rule)
        zero = _pvary(jnp.zeros_like(x_mb[0]), (PP_AXIS,))

        def tick(carry, t):
            incoming = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            inject = x_mb[mb_idx]
            act_in = jnp.where(idx == 0, inject, incoming)
            key = jax.random.fold_in(jax.random.fold_in(base, t), idx)
            out = stage_fn(stage_params, act_in, key)
            shifted = lax.ppermute(
                out, PP_AXIS, [(i, (i + 1) % S) for i in range(S)])
            return shifted, out

        _, outs = lax.scan(tick, zero, jnp.arange(M + S - 1))
        # last stage emits microbatch m at tick m + S - 1
        final = outs[S - 1:]
        mine = jnp.where(idx == S - 1, final, jnp.zeros_like(final))
        return lax.psum(mine, PP_AXIS)

    return run


def pipeline_spmd(stage_fn: Callable, num_stages: int, num_microbatches: int):
    """Keyless GPipe body: ``stage_fn(stage_params, x)`` (inference /
    deterministic stages).  Same schedule as :func:`pipeline_spmd_train`."""
    train = pipeline_spmd_train(lambda p, x, key: stage_fn(p, x),
                                num_stages, num_microbatches)

    def run(stage_params, x_mb):
        return train(stage_params, x_mb,
                     jax.random.key_data(jax.random.key(0)))

    return run


class PipelineModule:
    """Heterogeneous pipeline model: replicated embed → pp-sharded trunk of
    homogeneous blocks → replicated head.

    ≙ fleet.meta_parallel PipelineLayer + device_guard section programs
    (python/paddle/fluid/optimizer.py:3702 PipelineOptimizer splits by
    device_guard; paddle/fluid/framework/section_worker.cc runs the GPipe
    schedule).  TPU-first, the whole model is ONE jitted SPMD program:
    TrainStep recognizes this class and lays the stacked trunk params out as
    P('pp'), so stage weights live only on their pipeline rank while embed
    and head stay replicated; jax.grad differentiates straight through the
    scan+ppermute schedule (no hand-written backward pipeline).

    ``embed`` may be None (inputs feed the trunk directly); ``head`` may be
    None (trunk output is the model output).  Trunk blocks must be
    structurally identical and carry no buffers (batch-norm trunks are not
    pipelineable here — use group/layer norm, as transformer trunks do).
    """

    def __init__(self, embed, blocks: List, head, num_stages: int = None,
                 num_microbatches: int = 2, mesh=None):
        self.mesh = mesh or get_mesh()
        self.S = num_stages or self.mesh.shape.get(PP_AXIS, 1)
        if len(blocks) % self.S:
            raise ValueError(
                f"{len(blocks)} trunk blocks not divisible by {self.S} stages")
        self.embed = embed
        self.blocks = list(blocks)
        self.head = head
        self.M = num_microbatches
        self.per_stage = len(blocks) // self.S
        p0, b0 = F.layer_state(blocks[0])
        if b0:
            raise ValueError(
                "pipelined trunk blocks must be buffer-free (got buffers "
                f"{list(b0)}); replace batch-norm with layer/group norm")
        self.block_param_names = list(p0)

    # -- flat state ----------------------------------------------------------
    def flat_state(self):
        """(params, buffers) as flat dicts: 'embed::*', 'head::*' straight
        from the sublayers, 'pipe::*' the trunk stacked [S, per_stage, ...]."""
        params, buffers = {}, {}
        for tag, layer in (("embed", self.embed), ("head", self.head)):
            if layer is None:
                continue
            p, b = F.layer_state(layer)
            params.update({f"{tag}::{n}": v for n, v in p.items()})
            buffers.update({f"{tag}::{n}": v for n, v in b.items()})
        per_block = []
        for blk in self.blocks:
            p, _ = F.layer_state(blk)
            per_block.append(p)
        for n in self.block_param_names:
            stacked = jnp.stack([p[n] for p in per_block])
            params[f"pipe::{n}"] = stacked.reshape(
                (self.S, self.per_stage) + per_block[0][n].shape)
        return params, buffers

    def load_flat_state(self, params, buffers):
        """Write a flat state dict back into the eager sublayers."""
        for tag, layer in (("embed", self.embed), ("head", self.head)):
            if layer is None:
                continue
            p = {n[len(tag) + 2:]: v for n, v in params.items()
                 if n.startswith(tag + "::")}
            b = {n[len(tag) + 2:]: v for n, v in buffers.items()
                 if n.startswith(tag + "::")}
            F.load_layer_state(layer, p, b)
        for j, blk in enumerate(self.blocks):
            s, i = divmod(j, self.per_stage)
            F.load_layer_state(blk, {
                n: params[f"pipe::{n}"][s, i]
                for n in self.block_param_names}, None)

    def parameters(self):
        out = []
        for layer in (self.embed, self.head):
            if layer is not None:
                out.extend(layer.parameters())
        for blk in self.blocks:
            out.extend(blk.parameters())
        return out

    def state_dict(self):
        sd = {}
        for tag, layer in (("embed", self.embed), ("head", self.head)):
            if layer is not None:
                sd.update({f"{tag}.{k}": v
                           for k, v in layer.state_dict().items()})
        for j, blk in enumerate(self.blocks):
            sd.update({f"trunk.{j}.{k}": v
                       for k, v in blk.state_dict().items()})
        return sd

    def set_state_dict(self, sd):
        for tag, layer in (("embed", self.embed), ("head", self.head)):
            if layer is not None:
                layer.set_state_dict({k[len(tag) + 1:]: v
                                      for k, v in sd.items()
                                      if k.startswith(tag + ".")})
        for j, blk in enumerate(self.blocks):
            pre = f"trunk.{j}."
            blk.set_state_dict({k[len(pre):]: v for k, v in sd.items()
                                if k.startswith(pre)})

    # -- compiled body -------------------------------------------------------
    def build_body(self, remat: bool = False):
        """fn(stacked_params, x [B, ...], key_data) -> trunk output [B, ...],
        SPMD over the pp (and dp) mesh axes."""
        try:
            from jax import shard_map  # jax >= 0.6
        except ImportError:
            from jax.experimental.shard_map import shard_map
        block0 = self.blocks[0]
        names = self.block_param_names
        per_stage = self.per_stage
        S, M, mesh = self.S, self.M, self.mesh
        dp = mesh.shape.get(DP_AXIS, 1)

        def apply_block(x, block_params, key):
            params = dict(zip(names, block_params))
            return F.functional_call(block0, params, None, (x,),
                                     training=True, rng_key=key)

        if remat:
            # per-block rematerialization: the classic pipeline memory trade
            # (RecomputeOptimizer inside each section program)
            apply_block = jax.checkpoint(apply_block)

        def stage(stage_params, x, key):
            def body(x, i):
                bp = [stage_params[n][0, i] for n in names]
                return apply_block(x, bp, jax.random.fold_in(key, i)), None
            out, _ = lax.scan(body, x, jnp.arange(per_stage))
            return out

        run = pipeline_spmd_train(stage, S, M)
        param_specs = {n: P(PP_AXIS) for n in names}

        def fwd(stacked, x, key):
            if x.shape[0] % M:
                raise ValueError(
                    f"pipeline batch {x.shape[0]} not divisible by "
                    f"{M} microbatches")
            mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            bshard = DP_AXIS if (dp > 1 and mb.shape[1] % dp == 0) else None
            data_spec = P(None, bshard)
            out_mb = shard_map(
                run, mesh=mesh,
                in_specs=(param_specs, data_spec, P(None)),
                out_specs=data_spec,
            )({n: stacked[n] for n in names}, mb,
              jax.random.key_data(key))
            return out_mb.reshape((-1,) + out_mb.shape[2:])

        return fwd


class GPipe:
    """Pipeline a homogeneous stack of blocks (e.g. transformer layers).

    ≙ PipelineOptimizer + PipelineTrainer as one object. Blocks must share
    structure (same param pytree); layers are grouped into ``num_stages``
    stages of equal depth. Embedding/head layers stay replicated outside the
    pipelined trunk.
    """

    def __init__(self, blocks: List, num_stages: int = None, mesh=None,
                 num_microbatches: int = 2):
        self.mesh = mesh or get_mesh()
        self.S = num_stages or self.mesh.shape.get(PP_AXIS, 1)
        assert len(blocks) % self.S == 0, \
            f"{len(blocks)} blocks not divisible by {self.S} stages"
        self.blocks = blocks
        self.M = num_microbatches
        self.per_stage = len(blocks) // self.S

        # stack params: [n_blocks, ...] -> grouped [S, per_stage, ...]
        names = None
        all_params = []
        for b in blocks:
            p, _ = F.layer_state(b)
            if names is None:
                names = list(p)
            all_params.append([p[n] for n in names])
        self.param_names = names
        self.stacked = {
            n: jnp.stack([all_params[i][j] for i in range(len(blocks))])
                 .reshape((self.S, self.per_stage)
                          + all_params[0][j].shape)
            for j, n in enumerate(names)}
        # shard leading stage dim over pp
        self.stacked = {
            n: jax.device_put(v, NamedSharding(
                self.mesh, P(PP_AXIS) if self.mesh.shape.get(PP_AXIS, 1) > 1
                else P()))
            for n, v in self.stacked.items()}

    def _stage_fn(self):
        block0 = self.blocks[0]
        names = self.param_names
        per_stage = self.per_stage

        def apply_block(x, block_params):
            params = dict(zip(names, block_params))
            return F.functional_call(block0, params, None, (x,),
                                     training=False)

        def stage(stage_params, x):
            # inside shard_map the leading [S] dim is sliced to [1]:
            # stage_params[n]: [1, per_stage, ...]
            def body(x, i):
                bp = [stage_params[n][0, i] for n in names]
                return apply_block(x, bp), None
            out, _ = lax.scan(body, x, jnp.arange(per_stage))
            return out

        return stage

    def build_forward(self):
        """Return pure fn(stacked_params, x [B, ...]) -> y executed as SPMD
        over the pp (and dp) axes of the mesh."""
        try:
            from jax import shard_map  # jax >= 0.6
        except ImportError:
            from jax.experimental.shard_map import shard_map
        S, M = self.S, self.M
        body = pipeline_spmd(self._stage_fn(), S, M)
        mesh = self.mesh
        dp = mesh.shape.get(DP_AXIS, 1)

        param_specs = {n: P(PP_AXIS) for n in self.param_names}

        def fwd(stacked, x):
            mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            # shard the per-microbatch batch dim over dp only when divisible
            bshard = DP_AXIS if (dp > 1 and mb.shape[1] % dp == 0) else None
            data_spec = P(None, bshard)
            out_mb = shard_map(
                body, mesh=mesh,
                in_specs=(param_specs, data_spec),
                out_specs=data_spec,
            )({n: stacked[n] for n in self.param_names}, mb)
            return out_mb.reshape((-1,) + out_mb.shape[2:])

        return fwd

    def __call__(self, x):
        fwd = self.build_forward()
        arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(fwd(self.stacked, arr))
