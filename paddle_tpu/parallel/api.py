"""Sharding annotations: PartitionSpecs on parameters and batches.

Reference parity: where fleet meta-optimizers rewrite the Program to insert
c_allreduce/c_broadcast ops per tensor (python/paddle/distributed/fleet/
meta_optimizers/sharding_optimizer.py:103, fluid/transpiler/collective.py:209),
the TPU build attaches a ``PartitionSpec`` to each Parameter; pjit of the whole
step lets XLA GSPMD place the collectives. ``shard_parameter`` is therefore
the single annotation point for TP/ZeRO/EP layouts.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import get_mesh, DP_AXIS, SP_AXIS

SPEC_ATTR = "_partition_spec"
# rule-provenance attr analysis.autoshard stamps on specs IT applied; a
# hand shard_parameter call clears it so "hand wins" survives re-annotation
AUTOSHARD_SOURCE_ATTR = "_autoshard_rule"


def shard_parameter(param, spec):
    """Annotate a Parameter/Tensor with a PartitionSpec (lazy: applied when
    the train step is compiled, or immediately if a mesh is live and the
    array is concrete)."""
    if not isinstance(spec, P):
        spec = P(*spec) if isinstance(spec, (tuple, list)) else P(spec)
    setattr(param, SPEC_ATTR, spec)
    if getattr(param, AUTOSHARD_SOURCE_ATTR, None) is not None:
        # a direct (hand) annotation supersedes rule provenance — the
        # autoshard transform re-stamps the attr itself after calling here
        try:
            delattr(param, AUTOSHARD_SOURCE_ATTR)
        except AttributeError:
            pass
    return param


def annotation_source(param) -> Optional[str]:
    """``'<table>:<rule>'`` when analysis.autoshard applied this param's
    spec, None for hand annotations (or no annotation)."""
    return getattr(param, AUTOSHARD_SOURCE_ATTR, None)


def get_partition_spec(param) -> Optional[P]:
    return getattr(param, SPEC_ATTR, None)


def _clean_spec(spec: P, mesh) -> P:
    """Drop axis names the mesh doesn't have (lets TP-annotated models run
    unchanged on a pure-DP mesh)."""
    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in mesh.shape)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(entry if entry in mesh.shape else None)
    return P(*cleaned)


def named_shardings(layer_or_params, mesh=None) -> Dict[str, NamedSharding]:
    """{param_name: NamedSharding} honoring shard_parameter annotations;
    unannotated params are replicated."""
    mesh = mesh or get_mesh()
    if isinstance(layer_or_params, dict):
        items = [(n, None) for n in layer_or_params]
        specs = {}
    else:
        items = list(layer_or_params.named_parameters())
        specs = {n: get_partition_spec(p) for n, p in items}
    out = {}
    for n, _ in items:
        spec = specs.get(n) or P()
        out[n] = NamedSharding(mesh, _clean_spec(spec, mesh))
    return out


def replicated_sharding(mesh=None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())


def batch_sharding(mesh=None, ndim=2, seq_dim: Optional[int] = None) -> NamedSharding:
    """Shard the leading (batch) dim over dp, and optionally a sequence dim
    over sp (sequence/context parallelism)."""
    mesh = mesh or get_mesh()
    entries = [None] * ndim
    if DP_AXIS in mesh.shape:
        entries[0] = DP_AXIS
    if seq_dim is not None and SP_AXIS in mesh.shape:
        entries[seq_dim] = SP_AXIS
    return NamedSharding(mesh, P(*entries))


def shard_tensor(x, spec, mesh=None):
    """Place a concrete array/Tensor on the mesh with the given spec (the
    eager analogue of c_broadcast/scatter placement ops)."""
    from ..framework.tensor import Tensor
    mesh = mesh or get_mesh()
    if not isinstance(spec, P):
        spec = P(*spec) if isinstance(spec, (tuple, list)) else P(spec)
    sharding = NamedSharding(mesh, _clean_spec(spec, mesh))
    if isinstance(x, Tensor):
        x._value = jax.device_put(x._value, sharding)
        return x
    return jax.device_put(x, sharding)
