"""Sequence/context parallelism: ring attention over the ``sp`` mesh axis.

The 2.0-rc reference has NO long-context machinery (SURVEY.md §5: no ring
attention / context parallel anywhere in the tree) — its longest-sequence
tools are recompute and pipeline microbatching.  The TPU build makes
sequence sharding first-class per the build plan (§7): activations shard the
sequence dim over ``sp``, and attention runs as a RING — each shard holds
its local Q block, K/V blocks rotate around the ICI ring via
lax.ppermute, and softmax is accumulated online (flash-attention style
m/l/acc carry), so the full S×S score matrix never materializes and
communication overlaps compute around the ring.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

# lax.axis_size is ~jax 0.6; the classic psum-of-1 idiom is its exact
# definition and constant-folds to a Python int on older jax
_axis_size = getattr(lax, "axis_size", None) or (lambda a: lax.psum(1, a))

from .mesh import get_mesh, SP_AXIS, DP_AXIS


def _ring_attention_shard(q, k, v, *, scale, causal, axis):
    """Per-shard ring attention body (inside shard_map).

    q,k,v: [B, H, s_loc, D] local blocks; returns [B, H, s_loc, D].
    """
    S = _axis_size(axis)
    idx = lax.axis_index(axis)
    s_loc = q.shape[2]
    perm = [(i, (i + 1) % S) for i in range(S)]

    q_pos = idx * s_loc + jnp.arange(s_loc)  # global positions of my queries

    def step(carry, t):
        k_blk, v_blk, acc, m, l = carry
        # source rank of the kv block currently held: it has been shifted t
        # times from its home rank (idx - t)
        src = (idx - t) % S
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked blocks: exp(-inf - -inf) patterns
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m) - safe_m)
        corr = jnp.where(jnp.isneginf(m), 0.0, corr)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd",
                                                     p.astype(v_blk.dtype),
                                                     v_blk)
        k_next = lax.ppermute(k_blk, axis, perm)
        v_next = lax.ppermute(v_blk, axis, perm)
        return (k_next, v_next, acc_new, new_m, l_new), None

    # fresh accumulators must carry the same varying-manual-axes type as the
    # ring-shifted values they mix with; deriving them from q (rather than
    # bare zeros) inherits exactly q's VMA set (sp, and dp when batch-sharded)
    acc0 = (q * 0).astype(jnp.float32)
    m0 = jnp.sum(q, axis=-1).astype(jnp.float32) * 0 - jnp.inf
    l0 = jnp.sum(q, axis=-1).astype(jnp.float32) * 0
    (k_f, v_f, acc, m, l), _ = lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(S))
    out = acc / jnp.maximum(l[..., None], 1e-20)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, causal=False, axis=SP_AXIS):
    """Sequence-parallel attention.

    q,k,v: [B, H, S, D] arrays (or Tensors) with S shardable over the sp
    axis. Returns [B, H, S, D]. With sp absent/size 1, falls back to plain
    softmax attention (identical numerics — ring with S=1 is exact).
    """
    from ..framework.tensor import Tensor
    try:
        from jax import shard_map  # jax >= 0.6
    except ImportError:
        from jax.experimental.shard_map import shard_map

    unwrap = lambda x: x._value if isinstance(x, Tensor) else jnp.asarray(x)
    qa, ka, va = unwrap(q), unwrap(k), unwrap(v)
    mesh = mesh or get_mesh()
    sp = mesh.shape.get(axis, 1)
    scale = 1.0 / math.sqrt(qa.shape[-1])

    if sp <= 1:
        scores = jnp.einsum("bhqd,bhkd->bhqk", qa, ka) * scale
        if causal:
            s = qa.shape[2]
            mask = jnp.tril(jnp.ones((s, s), bool))
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(va.dtype), va)
        return Tensor(out) if isinstance(q, Tensor) else out

    dp = mesh.shape.get(DP_AXIS, 1)
    bspec = DP_AXIS if (dp > 1 and qa.shape[0] % dp == 0) else None
    spec = P(bspec, None, axis, None)
    body = functools.partial(_ring_attention_shard, scale=scale,
                             causal=causal, axis=axis)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    qa = jax.device_put(qa, NamedSharding(mesh, spec))
    ka = jax.device_put(ka, NamedSharding(mesh, spec))
    va = jax.device_put(va, NamedSharding(mesh, spec))
    out = fn(qa, ka, va)
    return Tensor(out) if isinstance(q, Tensor) else out
