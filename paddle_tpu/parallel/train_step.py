"""The compiled, sharded train step — the framework's execution heart.

Reference parity: this one class replaces the reference's entire hot path —
the Executor op loop (paddle/fluid/framework/executor.cc:473), the
ParallelExecutor SSA-graph engine with its AllReduceOpHandles
(parallel_executor.cc:613, details/all_reduce_op_handle.cc), the dygraph
Reducer's bucketed overlap-allreduce (imperative/reducer.cc:100), and the
optimizer graph ops (operators/optimizers/).

TPU-first: forward + loss + backward (jax.grad over the functional bridge)
+ optimizer update are ONE jitted function.  pjit/GSPMD shards it over the
global mesh from PartitionSpec annotations, so DP gradient all-reduce,
TP activation collectives and ZeRO-sharded optimizer states all come out of
the same compiled program, overlapped by the XLA scheduler (the hand-built
overlap machinery of reducer.cc is the compiler's job here).

Options map to reference strategies:
  remat=True            ≙ RecomputeOptimizer (fluid/optimizer.py:4533)
  zero=1                ≙ ShardingOptimizer stage-1 (sharding_optimizer.py:33)
  accumulate_steps=k    ≙ GradientMergeOptimizer (fluid/optimizer.py:5011)
  loss_scale / bf16     ≙ mixed-precision decorator (contrib/mixed_precision/)
"""
from __future__ import annotations

import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.tensor import Tensor
from ..framework import functional as F
from ..framework import flags as _flags
from ..profiler import RecordEvent, ledger as _ledger
from ..profiler import profiling_enabled as _prof_on
from ..profiler import span as _span
from ..profiler import tracing as _tracing
from ..profiler.metrics import default_registry as _registry
from .mesh import get_mesh, DP_AXIS
from .api import named_shardings, batch_sharding

# per-phase step-time breakdown (FLAGS_trace gates observation: the
# device_fence segment needs a block_until_ready the untraced hot path
# must not pay).  host_prep = feed placement; dispatch = handing the
# compiled step to the runtime (async); device_fence = blocking on the
# step's outputs.  Purely host-side timing — observing a step never
# changes the traced program or adds a compile key.
_STEP_PHASE = _registry().histogram(
    "train_step_phase_seconds",
    "Per-phase train-step wall segments under FLAGS_trace "
    "(host_prep / dispatch / device_fence).",
    labels=("phase",))

_NULL_CM = contextlib.nullcontext()     # shared no-op (reentrant, stateless)


def _as_array(x):
    if x is None:
        return None
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


def _global_put(v, sharding):
    """device_put that also works when ``sharding`` spans processes (the
    multi-host SPMD path: jax.distributed has formed a global mesh, as the
    reference's c_comm_init builds cross-node NCCL rings,
    operators/collective/c_comm_init_op.cc:123).  Host data is the SPMD
    contract — identical on every process — so each process materializes its
    addressable shards; single-device jax arrays are pulled to host first."""
    if jax.process_count() > 1 and isinstance(v, jax.Array):
        if not v.is_fully_addressable:
            return jax.device_put(v, sharding)  # global→global reshard
        v = np.asarray(v)
    return jax.device_put(v, sharding)


def _wrap_loss(loss_fn):
    """Run a Tensor-level loss (e.g. nn.CrossEntropyLoss) on raw arrays."""
    def run(out, label):
        from ..framework import core
        with core.no_grad_guard():
            o = Tensor(out) if not isinstance(out, Tensor) else out
            l = Tensor(label)
            res = loss_fn(o, l)
        return res._value if isinstance(res, Tensor) else res
    return run


class TrainStep:
    """Compile ``layer`` + ``loss_fn`` + ``optimizer`` into one sharded step.

    step semantics: ``loss = loss_fn(layer(*inputs), label)``; if ``loss_fn``
    is None the layer is called with the full batch and must return the loss.
    """

    def __init__(self, layer, optimizer, loss_fn=None, *, mesh=None,
                 remat: bool = False, zero: int = 0, accumulate_steps: int = 1,
                 donate: bool = True, seed: int = 0,
                 batch_spec=None, compute_dtype=None,
                 localsgd_k: int = 0, localsgd_begin: int = 1,
                 dgc_sparsity: float = 0.0, dgc_momentum: float = 0.9,
                 dgc_rampup_begin: int = 1,
                 sentinel: bool = None, grad_scaler=None,
                 checkpoint_manager=None):
        self.layer = layer
        self.optimizer = optimizer
        self.loss_fn = _wrap_loss(loss_fn) if loss_fn is not None else None
        self.mesh = mesh or get_mesh()
        self.remat = remat
        self.zero = zero
        self.accumulate_steps = int(accumulate_steps)
        self.seed = seed
        self.batch_spec = batch_spec
        self.compute_dtype = compute_dtype
        # LocalSGD (meta_optimizers/localsgd_optimizer.py parity): each dp
        # rank trains its OWN parameter copy for k steps, then copies are
        # averaged. TPU-shape: params/opt-state carry a leading dp-sharded
        # axis and the step vmaps over it — per-rank updates stay local
        # (zero collectives) until the periodic mean. localsgd_begin is the
        # warmup boundary: before it, every step syncs (adaptive ramp-in).
        self.localsgd_k = int(localsgd_k)
        self.localsgd_begin = int(localsgd_begin)
        if self.localsgd_k > 1 and zero:
            raise ValueError(
                "localsgd does not compose with sharding (zero) in this "
                "engine: per-rank replicas need the whole parameter tree "
                "local, ZeRO shards it over the same dp axis "
                "(strategy-ledger row localsgd+sharding)")
        # DGC (meta_optimizers/dgc_optimizer.py / operators/dgc_op.h
        # parity as an ENGINE mode): per-dp-rank momentum correction +
        # residual accumulation + sampled top-k sparsification BEFORE the
        # cross-rank mean — the wire-compression algorithm expressed as a
        # vmap over per-rank gradient shards.  The momentum lives INSIDE
        # the compression (DGCMomentumOptimizer), so pair it with a plain
        # SGD outer optimizer; with sparsity→0 the mode reduces exactly
        # to dense Momentum(dgc_momentum).
        self.dgc_sparsity = float(dgc_sparsity)
        self.dgc_momentum = float(dgc_momentum)
        self.dgc_rampup_begin = int(dgc_rampup_begin)
        if self.dgc_sparsity > 0 and (zero or self.localsgd_k > 1):
            raise ValueError(
                "dgc composes with neither sharding (zero) nor localsgd in "
                "this engine: its per-rank u/v state assumes replicated "
                "params and a single compression point per step; localsgd "
                "has no per-step gradient exchange to compress")
        if not (0.0 <= self.dgc_sparsity < 1.0):
            raise ValueError("dgc_sparsity must be in [0, 1)")
        if self.dgc_sparsity > 0 and getattr(optimizer, "_momentum", 0):
            raise ValueError(
                "dgc carries its own momentum correction (dgc_momentum); "
                "a Momentum outer optimizer would compound momentum twice "
                "— use plain SGD (fleet's strategy.dgc performs this swap "
                "and carries the coefficient automatically)")
        self._state = None
        self._compiled = None
        self._donate = donate
        self._seen_sigs = set()     # input signatures already compiled
        self._autoshard_plan = None  # set by init_state when autoshard on
        # -- fault-tolerance runtime (ISSUE 3) --------------------------------
        # numerics sentinel: None = follow FLAGS_train_sentinel at compile
        # time; an explicit True composes only with the standard engine
        # path (checked in compile()). grad_scaler: an amp.GradScaler —
        # when enabled, the loss is scaled IN-GRAPH (scale rides as a
        # traced operand, so scale changes never recompile), grads are
        # unscaled before the optimizer, and the sentinel verdict drives
        # the scaler's dynamic backoff.
        self._sentinel_requested = sentinel
        self._sentinel_active = False
        self._sentinel_names = ["loss"]
        self._bad_streak = 0
        self._host_step = 0
        self.grad_scaler = grad_scaler
        self.checkpoint_manager = checkpoint_manager

        from .pipeline import PipelineModule
        self._pipe = layer if isinstance(layer, PipelineModule) else None
        if self.localsgd_k > 1 and self._pipe is not None:
            raise ValueError("localsgd is a data-parallel strategy; it does "
                             "not compose with pipeline parallelism")
        if self.dgc_sparsity > 0 and self._pipe is not None:
            raise ValueError("dgc is a data-parallel strategy; it does not "
                             "compose with pipeline parallelism")
        if self._pipe is not None:
            # microbatching IS the gradient accumulation in a pipeline:
            # strategy accumulate_steps sets the GPipe microbatch count
            if self.accumulate_steps > 1:
                self._pipe.M = self.accumulate_steps
                self.accumulate_steps = 1
            self._pipe_fwd = self._pipe.build_body(remat=self.remat)

    # -- state ---------------------------------------------------------------
    def _param_sharding_tree(self, params):
        if self._pipe is not None:
            from .mesh import PP_AXIS
            shardings = {}
            for tag, layer in (("embed", self._pipe.embed),
                               ("head", self._pipe.head)):
                if layer is None:
                    continue
                sub = named_shardings(layer, self.mesh)
                shardings.update({f"{tag}::{n}": s for n, s in sub.items()})
            pp_live = self.mesh.shape.get(PP_AXIS, 1) > 1
            for n in params:
                if n.startswith("pipe::"):
                    shardings[n] = NamedSharding(
                        self.mesh, P(PP_AXIS) if pp_live else P())
        else:
            shardings = named_shardings(self.layer, self.mesh)
        return {n: shardings.get(n, NamedSharding(self.mesh, P()))
                for n in params}

    def _zero_spec(self, base_spec, shape):
        """Add a dp shard onto the first replicated, dp-divisible dim of a
        per-param array (the ZeRO layout rule)."""
        spec = list(base_spec) + [None] * (len(shape) - len(base_spec))

        def has_dp(entry):
            return entry == DP_AXIS or (
                isinstance(entry, (tuple, list)) and DP_AXIS in entry)
        if any(has_dp(e) for e in spec):
            return P(*spec)  # already ZeRO-laid-out (idempotent)
        if self.mesh.shape.get(DP_AXIS, 1) > 1:
            for d in range(len(shape)):
                if spec[d] is None and shape[d] % self.mesh.shape[DP_AXIS] == 0:
                    spec[d] = DP_AXIS
                    break
        return P(*spec)

    def _opt_sharding(self, param_shardings, opt_state):
        """Optimizer accumulators inherit their param's spec; with zero>=1 the
        first fully-replicated dim additionally shards over dp (ZeRO-1:
        sharding_optimizer.py:33 equivalent, but as a layout annotation)."""
        out = {}
        for sname, acc in opt_state.items():
            out[sname] = {}
            for pname, arr in acc.items():
                spec = param_shardings[pname].spec
                if self.zero >= 1:
                    spec = self._zero_spec(spec, arr.shape)
                out[sname][pname] = NamedSharding(self.mesh, spec)
        return out

    def _localsgd_degree(self):
        return self.mesh.shape.get(DP_AXIS, 1) if self.localsgd_k > 1 else 0

    def init_state(self):
        if self._pipe is not None:
            params, buffers = self._pipe.flat_state()
        else:
            # rules-driven auto-sharding (analysis.autoshard, ISSUE 9):
            # FLAGS_autoshard=apply annotates unannotated params from the
            # active PartitionRules table BEFORE the sharding tree below
            # reads the annotations; =propose publishes the plan without
            # mutating. One branch when off. The plan rides to the
            # compile-site lint (autoshard-conflict / sharding-coverage).
            from ..analysis.autoshard import maybe_autoshard
            self._autoshard_plan = maybe_autoshard(
                self.layer, mesh=self.mesh,
                site=f"train_step:{type(self.layer).__name__}")
            params, buffers = F.layer_state(self.layer)
        D = self._localsgd_degree()
        if D > 1:
            # per-rank copies: leading dp-sharded axis on params, buffers
            # and optimizer state; one copy per device, same memory as
            # replicated storage
            pshard = self._param_sharding_tree(params)
            rank_shard = {n: NamedSharding(self.mesh, P(DP_AXIS, *s.spec))
                          for n, s in pshard.items()}
            base = dict(params)
            opt_base = self.optimizer.functional_state(base)
            # accumulators matching the param shape inherit its rank spec;
            # scalar/odd-shaped ones just shard the leading rank axis
            oshard = {s: {n: (rank_shard[n] if v.shape == base[n].shape
                              else NamedSharding(self.mesh, P(DP_AXIS)))
                          for n, v in acc.items()}
                      for s, acc in opt_base.items()}
            buf_shard = NamedSharding(self.mesh, P(DP_AXIS))
            rep_n = lambda v: jnp.broadcast_to(v, (D,) + v.shape)
            params = {n: _global_put(rep_n(v), rank_shard[n])
                      for n, v in base.items()}
            buffers = {n: _global_put(rep_n(v), buf_shard)
                       for n, v in buffers.items()}
            opt_state = {s: {n: _global_put(rep_n(v), oshard[s][n])
                             for n, v in acc.items()}
                         for s, acc in opt_base.items()}
            rep = NamedSharding(self.mesh, P())
            self._state = {
                "params": params, "buffers": buffers, "opt": opt_state,
                "step": _global_put(np.zeros((), np.int32), rep),
            }
            self._shardings = {
                "params": rank_shard,
                "buffers": {n: buf_shard for n in buffers},
                "opt": oshard,
                "step": rep,
            }
            self._grad_shardings = None
            return self._state
        pshard = self._param_sharding_tree(params)
        if self.zero >= 3:
            # ZeRO-3: parameters themselves are stored dp-sharded; GSPMD
            # all-gathers each param at its use sites inside the step
            # (sharding_optimizer.py stage-3 param shard + broadcast)
            pshard = {n: NamedSharding(
                self.mesh, self._zero_spec(s.spec, params[n].shape))
                for n, s in pshard.items()}
        if self.zero >= 2:
            # ZeRO-2: gradients leave the backward pass reduce-scattered
            # over dp (sharding_optimizer.py stage-2 grad shard); the same
            # layout rule as the opt state so the update is local
            self._grad_shardings = {
                n: NamedSharding(self.mesh,
                                 self._zero_spec(pshard[n].spec,
                                                 params[n].shape))
                for n in params}
        else:
            self._grad_shardings = None
        params = {n: _global_put(v, pshard[n]) for n, v in params.items()}
        rep = NamedSharding(self.mesh, P())
        buffers = {n: _global_put(v, rep) for n, v in buffers.items()}
        opt_state = self.optimizer.functional_state(params)
        oshard = self._opt_sharding(pshard, opt_state)
        opt_state = {s: {n: _global_put(v, oshard[s][n])
                         for n, v in acc.items()}
                     for s, acc in opt_state.items()}
        self._state = {
            "params": params, "buffers": buffers, "opt": opt_state,
            "step": _global_put(np.zeros((), np.int32), rep),
        }
        self._shardings = {"params": pshard, "buffers": {n: rep for n in buffers},
                          "opt": oshard, "step": rep}
        if self.dgc_sparsity > 0:
            # per-rank momentum-correction (u) and residual (v) buffers,
            # one slice per dp rank (dgc_op.h U/V state)
            D = max(1, self.mesh.shape.get(DP_AXIS, 1))
            ushard = {n: NamedSharding(self.mesh, P(DP_AXIS, *pshard[n].spec))
                      for n in params}
            for tag in ("dgc_u", "dgc_v"):
                self._state[tag] = {
                    n: _global_put(np.zeros((D,) + tuple(v.shape),
                                            np.float32), ushard[n])
                    for n, v in params.items()}
                self._shardings[tag] = ushard
        return self._state

    @property
    def state(self):
        if self._state is None:
            self.init_state()
        return self._state

    # -- step function -------------------------------------------------------
    @staticmethod
    def _cast_compute(params, buffers, inputs, cd):
        """Low-precision compute cast for params and float inputs. Buffers
        (BN running stats) deliberately stay fp32: each op re-casts its
        output to the activation dtype, so stats never leak fp32 into the
        compute path, and casting them would round-trip the running
        averages through bf16 every step (losing small-momentum updates).
        Returns (params, buffers, inputs)."""
        fl = lambda v: jnp.issubdtype(v.dtype, jnp.floating)
        params = {n: (v.astype(cd) if fl(v) else v)
                  for n, v in params.items()}
        inputs = tuple(x.astype(cd) if x is not None and fl(x) else x
                       for x in inputs)
        return params, buffers, inputs

    def _pipe_loss_of(self, params, buffers, inputs, label, rng_key):
        """Pipelined forward: embed (replicated) → GPipe trunk over pp →
        head (replicated) → loss.  One SPMD program; jax.grad reverses the
        whole schedule."""
        if self.compute_dtype is not None:
            params, buffers, inputs = self._cast_compute(
                params, buffers, inputs, self.compute_dtype)

        def sub(tree, tag):
            pre = tag + "::"
            return {n[len(pre):]: v for n, v in tree.items()
                    if n.startswith(pre)}

        pipe = self._pipe
        new_buffers = dict(buffers)
        if pipe.embed is not None:
            x, eb = F.functional_call(
                pipe.embed, sub(params, "embed"), sub(buffers, "embed"),
                inputs, training=True, rng_key=rng_key, mutable_buffers=True)
            if isinstance(x, (tuple, list)):
                x = x[0]
            new_buffers.update({f"embed::{n}": v for n, v in eb.items()})
        else:
            x = inputs[0]

        h = self._pipe_fwd(sub(params, "pipe"), x,
                           jax.random.fold_in(rng_key, 1))

        if pipe.head is not None:
            head_args = (h,) if self.loss_fn is not None or label is None \
                else (h, label)
            out, hb = F.functional_call(
                pipe.head, sub(params, "head"), sub(buffers, "head"),
                head_args, training=True,
                rng_key=jax.random.fold_in(rng_key, 2), mutable_buffers=True)
            new_buffers.update({f"head::{n}": v for n, v in hb.items()})
        else:
            out = h
        if isinstance(out, (tuple, list)):
            out = out[0]
        loss = self.loss_fn(out, label) if self.loss_fn is not None else out
        return loss.astype(jnp.float32).mean(), new_buffers

    def _loss_of(self, params, buffers, inputs, label, rng_key):
        if self.compute_dtype is not None:
            params, buffers, inputs = self._cast_compute(
                params, buffers, inputs, self.compute_dtype)
        if self.loss_fn is None:
            args = inputs if label is None else inputs + (label,)
            out, new_buffers = F.functional_call(
                self.layer, params, buffers, args, training=True,
                rng_key=rng_key, mutable_buffers=True)
            loss = out[0] if isinstance(out, (tuple, list)) else out
        else:
            out, new_buffers = F.functional_call(
                self.layer, params, buffers, inputs, training=True,
                rng_key=rng_key, mutable_buffers=True)
            if isinstance(out, (tuple, list)):
                out = out[0]
            loss = self.loss_fn(out, label)
        return loss.astype(jnp.float32).mean(), new_buffers

    def _rank_grad(self, loss_of, params, buffers, mb_in, mb_lb, key):
        """(loss, grads, new_buffers) for ONE dp rank's batch shard,
        gradient-merging over ``accumulate_steps`` microbatches first when
        configured.  This is GradientMergeOptimizer composed INSIDE the
        per-rank leg of localsgd/dgc (VERDICT r5 #7): the accumulation
        happens strictly BEFORE any compression or replica averaging, the
        same ordering fleet's strategy_compiler.py ranks the reference
        meta-optimizers in."""
        grad_fn = jax.value_and_grad(loss_of, has_aux=True)
        k = self.accumulate_steps
        if k <= 1:
            (loss, nb), g = grad_fn(params, buffers, mb_in, mb_lb, key)
            return loss, g, nb

        def split(x):
            if x is None:
                return None
            return x.reshape((k, x.shape[0] // k) + x.shape[1:])

        def micro(carry, mb):
            g_acc, l_acc, buf = carry
            mi, ml = mb
            (loss, buf), g = grad_fn(params, buf, mi, ml, key)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            return (g_acc, l_acc + loss, buf), None

        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (g, loss, nb), _ = jax.lax.scan(
            micro, (g0, jnp.float32(0.0), buffers),
            (tuple(split(x) for x in mb_in),
             None if mb_lb is None else split(mb_lb)))
        g = jax.tree_util.tree_map(lambda q: q / k, g)
        return loss / k, g, nb

    def _build_localsgd_step(self):
        """LocalSGD step: vmap the (grad + update) over the per-rank leading
        axis — each dp rank advances its own replica from its own batch
        shard; every localsgd_k-th step (and every step before
        localsgd_begin) the replicas are averaged
        (localsgd_optimizer.py:440's allreduce-of-params, here one mean
        over the dp-sharded axis)."""
        loss_of = self._loss_of
        if self.remat:
            loss_of = jax.checkpoint(loss_of, static_argnums=())
        D = self._localsgd_degree()
        k = self.localsgd_k
        begin = self.localsgd_begin

        def step(state, inputs, label, lr, scale):
            new_step = state["step"] + 1
            base_key = jax.random.fold_in(jax.random.key(self.seed), new_step)

            def per_rank(p, b, o, mb_in, mb_lb, ridx):
                key = jax.random.fold_in(base_key, ridx)
                loss, g, nb = self._rank_grad(loss_of, p, b, mb_in, mb_lb,
                                              key)
                np_, no = self.optimizer.functional_apply(p, g, o, new_step,
                                                          lr)
                return loss, np_, nb, no

            def split(x):
                if x is None:
                    return None
                return x.reshape((D, x.shape[0] // D) + x.shape[1:])

            mb_in = tuple(split(x) for x in inputs)
            mb_lb = None if label is None else split(label)
            loss, new_params, new_buffers, new_opt = jax.vmap(
                per_rank, in_axes=(0, 0, 0, 0, 0, 0))(
                state["params"], state["buffers"], state["opt"],
                mb_in, mb_lb, jnp.arange(D))

            do_sync = jnp.logical_or(new_step < begin, new_step % k == 0)

            def avg(tree):
                return jax.tree_util.tree_map(
                    lambda v: jnp.broadcast_to(
                        jnp.mean(v, axis=0, keepdims=True,
                                 dtype=v.dtype if jnp.issubdtype(
                                     v.dtype, jnp.floating) else None),
                        v.shape) if jnp.issubdtype(v.dtype, jnp.floating)
                    else v,
                    tree)

            new_params, new_buffers = jax.lax.cond(
                do_sync, lambda t: (avg(t[0]), avg(t[1])), lambda t: t,
                (new_params, new_buffers))
            return {"params": new_params, "buffers": new_buffers,
                    "opt": new_opt, "step": new_step}, loss.mean()

        return step

    def _build_dgc_step(self):
        """DGC engine step (dgc_op.h + dgc_optimizer.py): the batch splits
        into dp shards; each rank's gradient passes momentum correction
        (u = m·u + g), residual accumulation (v += u), and sampled-top-k
        sparsification; the cross-rank mean runs on the SPARSE tensors and
        u/v keep the unsent mass (+ the sent mass is cleared from both).
        Before dgc_rampup_begin the step transmits v densely (and clears
        it), which makes the mode EXACTLY dense Momentum(dgc_momentum) —
        the rampup contract the reference's DGCMomentumOptimizer keeps."""
        loss_of = self._loss_of
        if self.remat:
            loss_of = jax.checkpoint(loss_of, static_argnums=())
        D = max(1, self.mesh.shape.get(DP_AXIS, 1))
        m = self.dgc_momentum
        sparsity = self.dgc_sparsity
        rampup = self.dgc_rampup_begin

        def sparsify(v):
            """Per-rank sampled threshold (the reference estimates the
            top-k cut from a gradient sample, dgc_op.h k-select)."""
            flat = jnp.abs(v.reshape(D, -1))
            n = flat.shape[1]
            stride = max(1, n // 4096)
            samp = flat[:, ::stride]
            thr = jnp.quantile(samp, sparsity, axis=1)      # [D]
            shape = (D,) + (1,) * (v.ndim - 1)
            return (jnp.abs(v) >= thr.reshape(shape)).astype(v.dtype)

        def step(state, inputs, label, lr, scale):
            new_step = state["step"] + 1
            base_key = jax.random.fold_in(jax.random.key(self.seed),
                                          new_step)

            def split(x):
                if x is None:
                    return None
                return x.reshape((D, x.shape[0] // D) + x.shape[1:])

            def per_rank(mb_in, mb_lb, ridx):
                key = jax.random.fold_in(base_key, ridx)
                # gradient_merge composes INSIDE the rank leg: the mean
                # microbatch gradient forms BEFORE momentum correction /
                # sparsification, so compression sees the merged gradient
                loss, g, nb = self._rank_grad(loss_of, state["params"],
                                              state["buffers"], mb_in,
                                              mb_lb, key)
                return loss, g, nb

            mb_in = tuple(split(x) for x in inputs)
            mb_lb = None if label is None else split(label)
            loss, grads, new_buffers = jax.vmap(
                per_rank, in_axes=(0, 0, 0))(mb_in, mb_lb, jnp.arange(D))
            # replicated buffers: consensus = mean of the rank copies
            new_buffers = {
                n: (jnp.mean(v, axis=0)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v[0])
                for n, v in new_buffers.items()}

            def compress(g, u, v):
                u_m = m * u + g.astype(jnp.float32)
                dense = new_step < rampup
                # rampup: plain Momentum — persistent velocity, nothing
                # masked (DGCMomentumOptimizer 'behaves as normal Momentum
                # before rampup_begin_step')
                # dgc: residual accumulation + top-k masking; sent
                # coordinates clear BOTH u (momentum factor masking) and v
                v_s = v + u_m
                mask = sparsify(v_s)
                pick = lambda a, b: jnp.where(dense, a, b)  # noqa: E731
                send = pick(u_m, v_s * mask)
                new_u = pick(u_m, u_m * (1.0 - mask))
                new_v = pick(v, v_s * (1.0 - mask))
                return send, new_u, new_v

            send, new_u, new_v = {}, {}, {}
            for n, g in grads.items():
                s, nu, nv = compress(g, state["dgc_u"][n],
                                     state["dgc_v"][n])
                send[n] = jnp.mean(s, axis=0)        # cross-rank reduce
                new_u[n], new_v[n] = nu, nv

            new_params, new_opt = self.optimizer.functional_apply(
                state["params"], send, state["opt"], new_step, lr)
            return {"params": new_params, "buffers": new_buffers,
                    "opt": new_opt, "step": new_step,
                    "dgc_u": new_u, "dgc_v": new_v}, loss.mean()

        return step

    # -- numerics sentinel ----------------------------------------------------
    def _resolve_sentinel(self) -> bool:
        """Static (trace-time) sentinel decision — the off-path cost is
        exactly this one Python branch, like PR 1's profiler gates."""
        req = self._sentinel_requested
        incompatible = self.dgc_sparsity > 0 or self._localsgd_degree() > 1
        if req is None:
            req = bool(_flags.flag("train_sentinel"))
            if req and incompatible:
                import warnings
                warnings.warn(
                    "FLAGS_train_sentinel: the in-graph numerics sentinel "
                    "does not compose with the localsgd/dgc engine paths "
                    "yet (per-rank replica state has no single "
                    "skip-step select point); running without it")
                req = False
        elif req and incompatible:
            raise ValueError(
                "sentinel=True does not compose with localsgd/dgc: their "
                "per-rank replica state has no single skip-step select "
                "point in this engine")
        return bool(req)

    def _fault_nan_steps(self):
        """Trace-time fault plan consultation (testing/faults.py): steps
        at which every gradient leaf is overwritten with NaN IN-GRAPH, so
        injected blow-ups travel the exact path a real one does."""
        from ..testing.faults import active_plan
        plan = active_plan()
        return tuple(plan.nan_grad_steps()) if plan is not None else ()

    def _build_step(self):
        if self.dgc_sparsity > 0:
            return self._build_dgc_step()
        if self._localsgd_degree() > 1:
            return self._build_localsgd_step()
        if self._pipe is not None:
            # remat happens per trunk block inside build_body
            loss_of = self._pipe_loss_of
        else:
            loss_of = self._loss_of
            if self.remat:
                # RecomputeOptimizer ≙ jax.checkpoint over the whole loss fn;
                # per-layer policies live in nn layers via recompute() wrapper.
                loss_of = jax.checkpoint(loss_of, static_argnums=())

        acc_k = self.accumulate_steps
        sentinel = self._sentinel_active
        use_scaler = self.grad_scaler is not None and \
            self.grad_scaler.is_enable()
        nan_steps = self._fault_nan_steps()

        def constrain_grads(grads):
            if self._grad_shardings is None:
                return grads
            return {n: jax.lax.with_sharding_constraint(
                g, self._grad_shardings[n]) for n, g in grads.items()}

        def step(state, inputs, label, lr, scale):
            new_step = state["step"] + 1
            rng_key = jax.random.fold_in(jax.random.key(self.seed),
                                         new_step)
            if use_scaler:
                # loss scaling INSIDE the graph (loss_scaler.py parity for
                # fp16): scale is a traced operand, so dynamic-scale
                # changes never force a recompile
                def scaled_loss_of(p, b, i, l, k):
                    loss, nb = loss_of(p, b, i, l, k)
                    return loss * scale, nb
                grad_fn = jax.value_and_grad(scaled_loss_of, has_aux=True)
            else:
                grad_fn = jax.value_and_grad(loss_of, has_aux=True)

            if acc_k > 1:
                # GradientMerge: microbatch scan accumulating grads; the
                # optimizer runs once on the mean gradient.
                def micro(carry, mb):
                    g_acc, l_acc, buf = carry
                    mb_in, mb_lb = mb
                    (loss, buf), g = grad_fn(state["params"], buf, mb_in,
                                             mb_lb, rng_key)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    return (g_acc, l_acc + loss, buf), None

                def split(x):
                    if x is None:
                        return None
                    return x.reshape((acc_k, x.shape[0] // acc_k) + x.shape[1:])
                mb_inputs = tuple(split(x) for x in inputs)
                mb_label = None if label is None else split(label)
                g0 = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), state["params"])
                (grads, loss, new_buffers), _ = jax.lax.scan(
                    micro, (g0, jnp.float32(0.0), state["buffers"]),
                    (mb_inputs, mb_label))
                grads = jax.tree_util.tree_map(lambda g: g / acc_k, grads)
                loss = loss / acc_k
            else:
                (loss, new_buffers), grads = grad_fn(
                    state["params"], state["buffers"], inputs, label, rng_key)
            if use_scaler:
                # check_finite_and_unscale parity: grads (and the reported
                # loss) leave the scaled domain before the sentinel check
                # and the optimizer update
                inv = 1.0 / scale
                grads = {n: g * inv for n, g in grads.items()}
                loss = loss * inv
            if nan_steps:
                bad = jnp.zeros((), bool)
                for s in nan_steps:
                    bad = jnp.logical_or(bad, new_step == s)
                grads = {n: jnp.where(bad, jnp.full_like(g, jnp.nan), g)
                         for n, g in grads.items()}
            grads = constrain_grads(grads)

            new_params, new_opt = self.optimizer.functional_apply(
                state["params"], grads, state["opt"], new_step, lr)
            new_state = {"params": new_params, "buffers": new_buffers,
                         "opt": new_opt, "step": new_step}
            if not sentinel:
                return new_state, loss
            # ONE fused reduction over loss + every gradient leaf (sorted
            # order matches self._sentinel_names); XLA folds the per-leaf
            # isfinite/all into the epilogue of the grad all-reduce it
            # already schedules — there is no extra HBM pass
            finite_vec = jnp.stack(
                [jnp.all(jnp.isfinite(loss))] +
                [jnp.all(jnp.isfinite(grads[n])) for n in sorted(grads)])
            finite = jnp.all(finite_vec)
            bad_idx = jnp.argmax(jnp.logical_not(finite_vec))
            # skip-step: a non-finite step commits NOTHING — params, opt
            # accumulators and BN buffers all keep their previous values
            # (a poisoned batch must not leak through running stats); the
            # step counter alone advances so rng streams/logs move on
            select = lambda new, old: jax.tree_util.tree_map(  # noqa: E731
                lambda n, o: jnp.where(finite, n, o), new, old)
            new_state = {"params": select(new_params, state["params"]),
                         "buffers": select(new_buffers, state["buffers"]),
                         "opt": select(new_opt, state["opt"]),
                         "step": new_step}
            return new_state, (loss, finite, bad_idx)

        return step

    def compile(self):
        if self._compiled is not None:
            return self._compiled
        self.state  # materialize
        self._sentinel_active = self._resolve_sentinel()
        if self._sentinel_active:
            self._sentinel_names = ["loss"] + sorted(
                self._state["params"])   # stack order of finite_vec
        step = self._build_step()
        self._step_fn = step     # raw (unjitted) step: graph-lint traces it
        state_shardings = dict(self._shardings)
        rep = NamedSharding(self.mesh, P())
        loss_out = (rep, rep, rep) if self._sentinel_active else rep
        self._compiled = jax.jit(
            step,
            in_shardings=(state_shardings, None, None, None, None),
            out_shardings=(state_shardings, loss_out),
            donate_argnums=(0,) if self._donate else (),
        )
        return self._compiled

    # -- AOT access (lowered-executable surface, ISSUE 8) --------------------
    def aot_lower(self, inputs, label=None):
        """AOT-lower the compiled sharded step for example ``inputs``
        WITHOUT executing it.  Returns ``jax.stages.Lowered``;
        ``.compile()`` yields the executable whose ``as_text()`` /
        ``cost_analysis()`` / ``memory_analysis()`` the HLO audit
        (``analysis.hlo``) inspects — abstract eval + XLA compile only,
        so pod-width virtual meshes work with no hardware attached."""
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)

        def conv(x):
            if x is None or isinstance(x, jax.ShapeDtypeStruct):
                return x
            return _as_array(x)

        inputs = tuple(conv(x) for x in inputs)
        label = conv(label)
        # place real arrays under the same batch shardings the eager entry
        # uses — the audited program must shard its feed exactly like the
        # executed one (ShapeDtypeStructs pass through unplaced)
        if inputs and not isinstance(inputs[0], jax.ShapeDtypeStruct):
            put = self._feed_placer(inputs)
            inputs = tuple(put(x) for x in inputs)
            label = put(label) if not isinstance(
                label, jax.ShapeDtypeStruct) else label
        fn = self.compile()
        lr = np.float32(self.optimizer.get_lr())
        return fn.lower(self.state, inputs, label, lr, np.float32(1.0))

    def aot_compile(self, inputs, label=None):
        """``aot_lower(...).compile()`` — the compiled executable, never
        dispatched.  Under FLAGS_executable_cache the XLA compile is
        served from the persistent executable cache, keyed by the sha256
        of the lowered StableHLO module itself — exact program identity
        (mesh, shardings, donation, sentinel and every lowering flag are
        all in the module text), so the cache can never substitute a
        different program; lowering (the cheap half) always runs, the
        XLA compile (the expensive half) loads.  HLO-audit lowerings
        ride this path, so pod-scale audits pay one compile per
        signature per CLUSTER, not per host."""
        lowered = self.aot_lower(inputs, label)
        from ..jit import persistent_cache as _pcache
        if not _pcache.enabled():       # off-path: one branch
            return lowered.compile()
        import hashlib
        hlo_sha = hashlib.sha256(
            lowered.as_text().encode()).hexdigest()
        site = f"train_step:{type(self.layer).__name__}:{id(self):#x}"
        compiled, _loaded = _pcache.load_or_compile(
            lowered.compile,
            site=site, kind="train_step_aot",
            key=(("arg:hlo_sha256", hlo_sha[:16]),),
            extra_key=("train_step_hlo", hlo_sha),
            # aot_compile never ledgered its compiles (the HLO audit
            # ledgers its own lowering at kind hlo_audit) — keep that;
            # loads still ledger as cache_load per the warm-start proof
            ledger_miss=False)
        return compiled

    # -- eager entry ---------------------------------------------------------
    def _feed_placer(self, inputs):
        """The batch-placement rule shared by the eager entry and the AOT
        lowering path (the audited program must shard its feed exactly
        like the executed one): returns ``put(x)`` mapping one host/global
        array onto its mesh sharding."""
        dp = self.mesh.shape.get(DP_AXIS, 1)
        lead_ndim = inputs[0].ndim
        nproc = jax.process_count()
        local_dp = dp // nproc if (nproc > 1 and dp > 1 and
                                   dp % nproc == 0) else dp

        def put(x):
            if x is None:
                return None
            # multi-host SPMD: a global array (e.g. built by the caller with
            # make_array_from_process_local_data) passes straight through
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                return x
            if nproc > 1 and dp > 1 and dp % nproc != 0:
                # host-fed local shards can only tile the dp axis when every
                # process owns the same whole number of dp slots; otherwise
                # the shard boundaries straddle process device halves.
                # (Caller-built global arrays took the passthrough above.)
                raise ValueError(
                    f"multi-process feed: dp degree {dp} must be divisible "
                    f"by the process count {nproc} (each process feeds "
                    "whole dp slots); reshape the mesh or build the global "
                    "arrays yourself with "
                    "jax.make_array_from_process_local_data")
            # explicit batch_spec only applies to arrays of the lead rank;
            # lower-rank labels get their own rank-matched sharding
            if self.batch_spec is not None and x.ndim == lead_ndim:
                sh = self.batch_spec
            elif x.ndim >= 1 and dp > 1 and x.shape[0] % local_dp == 0:
                sh = batch_sharding(self.mesh, ndim=x.ndim)
            elif nproc > 1 and dp > 1:
                # replication across processes assumes IDENTICAL host data
                # on every rank — but with a live dp axis each rank feeds
                # its OWN shard, so 'replicating' would commit different
                # values per rank and silently diverge the SPMD state.
                raise ValueError(
                    f"multi-process feed: local batch dim {x.shape[0]} is "
                    f"not divisible by this process's dp slots ({local_dp}"
                    f"; dp={dp} over {nproc} processes) — pad the batch or "
                    "build the global array yourself with "
                    "jax.make_array_from_process_local_data")
            else:
                # no dp axis (or single-process indivisible batch):
                # replicate. Multi-process contract: with dp==1 every rank
                # must feed the SAME full batch (there is no shard to own).
                return _global_put(x, NamedSharding(self.mesh, P()))
            if nproc > 1:
                # each process feeds its LOCAL batch shard; assemble the
                # global dp-sharded array (the multi-host DataLoader contract
                # — reference: each trainer reads its own file split,
                # fleet/data_generator + dist-train doc)
                with _span("train_step::collective_assemble"):
                    return jax.make_array_from_process_local_data(
                        sh, np.asarray(x))
            return jax.device_put(x, sh)

        return put

    def __call__(self, inputs, label=None):
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        inputs = tuple(_as_array(x) for x in inputs)
        label = None if label is None else _as_array(label)

        dp = self.mesh.shape.get(DP_AXIS, 1)
        lead_ndim = inputs[0].ndim
        nproc = jax.process_count()
        local_dp = dp // nproc if (nproc > 1 and dp > 1 and
                                   dp % nproc == 0) else dp
        if self._localsgd_degree() > 1 or self.dgc_sparsity > 0:
            # each rank computes over its own shard, so there is no
            # replicate fallback; a caller-built global array carries the
            # GLOBAL batch while a host-fed array carries this process's
            # local slice — validate each against the dp slots it covers
            x0 = inputs[0]
            is_global = isinstance(x0, jax.Array) and \
                not x0.is_fully_addressable
            need = dp if is_global else max(1, local_dp)
            # with gradient_merge composed into the rank leg, each rank's
            # shard further splits into accumulate_steps microbatches
            need *= max(1, self.accumulate_steps)
            if x0.shape[0] % need != 0:
                raise ValueError(
                    f"localsgd/dgc need the "
                    f"{'global' if is_global else 'per-process'} batch "
                    f"({x0.shape[0]}) divisible by the "
                    f"{'dp degree' if is_global else 'local dp slots'} "
                    f"× accumulate_steps "
                    f"({need}; dp={dp} over {nproc} processes, "
                    f"accumulate_steps={self.accumulate_steps})")

        put = self._feed_placer(inputs)

        prof = _prof_on()
        # per-step sampling decision for the phase breakdown (off = one
        # branch; sample mode keeps every k-th step)
        tr = _tracing.should_sample() if _tracing.enabled() else False
        t_prep0 = time.monotonic() if tr else 0.0
        with _span("train_step::data_feed"):
            inputs = tuple(put(x) for x in inputs)
            label = put(label)
        if tr:
            _STEP_PHASE.labels(phase="host_prep").observe(
                time.monotonic() - t_prep0)
        fn = self.compile()
        # host scalars (not committed device arrays) so the jit treats them
        # as process-replicated under a multi-host mesh; the loss scale is
        # a traced operand so GradScaler backoff never recompiles
        lr = np.float32(self.optimizer.get_lr())
        scaler = self.grad_scaler if (self.grad_scaler is not None and
                                      self.grad_scaler.is_enable()) else None
        scale = np.float32(scaler.get_loss_scaling() if scaler else 1.0)
        # retrace detection: jax.jit silently recompiles on a new input
        # signature — ledger it like any other cache miss.  Entries are
        # path-labeled and carry the weak-type bit: a python scalar fed
        # one step and a committed array the next LOOK identical by
        # shape/dtype but compile different programs, and the ledger diff
        # must name the argument that moved, not say "key unchanged".
        def _arg_sig(path, x):
            # "arg:" prefix = the ledger's labeled-leaf convention: the
            # cache-key diff prints this path instead of a positional index
            if x is None:
                return ("arg:" + path, "none")
            return ("arg:" + path, tuple(x.shape), str(x.dtype),
                    "weak" if getattr(x, "weak_type", False) else "strong")

        sig = (tuple(_arg_sig(f"inputs[{i}]", x)
                     for i, x in enumerate(inputs))
               + (_arg_sig("label", label),))
        fresh = sig not in self._seen_sigs
        site = f"train_step:{type(self.layer).__name__}:{id(self):#x}"
        if fresh:
            from ..analysis import lint_enabled as _lint_on
            if _lint_on():
                # graph lint over the about-to-compile step (abstract
                # eval only, amortized per retrace): donation and
                # sharding-coverage read the compile-site metadata; in
                # error mode this raises BEFORE the step ever runs
                from ..analysis import lint_traced
                from .api import annotation_source, get_partition_spec
                specs = None
                extra = {}
                if self._pipe is None:
                    try:
                        named = list(self.layer.named_parameters())
                        specs = {n: get_partition_spec(p)
                                 for n, p in named}
                        # hand-vs-rule provenance for autoshard-conflict
                        extra["autoshard_sources"] = {
                            n: annotation_source(p) for n, p in named}
                    except Exception:
                        specs = None
                        extra = {}
                if self._autoshard_plan is not None:
                    extra["autoshard_plan"] = self._autoshard_plan
                lint_traced(self._step_fn,
                            (self.state, inputs, label, lr, scale),
                            site=site, kind="train_step", cache_key=sig,
                            prev_key=_ledger.last_key(site),
                            donate=self._donate, mesh=self.mesh,
                            params=self.state["params"],
                            partition_specs=specs, extra=extra)
            from ..analysis.hlo import audit_enabled as _hlo_audit_on
            if _hlo_audit_on():
                # compiled-program audit (analysis.hlo): AOT-relower the
                # exact signature about to compile and inspect the
                # partitioned HLO (collective census, ZeRO layout
                # contract, per-device memory) BEFORE the step executes —
                # error mode raises with the state untouched.  Costs one
                # extra XLA compile per fresh signature; one branch when
                # off.
                from ..analysis.hlo import audit_train_step
                audit_train_step(self, inputs, label, site="hlo:" + site)
            self._seen_sigs.add(sig)
            t0 = time.perf_counter()
            with _span("train_step::compile"):
                self._state, out = fn(self.state, inputs, label, lr, scale)
            _ledger.record_compile(site, "train_step", sig,
                                   (time.perf_counter() - t0) * 1e3)
        else:
            _ledger.record_cache_hit(site)
            if prof or tr:
                # fence on the loss so the span is device time, not the
                # async dispatch; the same fence splits the traced
                # dispatch / device_fence histogram segments
                rec = RecordEvent("train_step::device_execute") if prof \
                    else _NULL_CM
                t_d0 = time.monotonic()
                with rec:
                    self._state, out = fn(self.state, inputs, label, lr,
                                          scale)
                    t_d1 = time.monotonic()
                    jax.block_until_ready(out)
                if tr:
                    t_d2 = time.monotonic()
                    _STEP_PHASE.labels(phase="dispatch").observe(
                        t_d1 - t_d0)
                    _STEP_PHASE.labels(phase="device_fence").observe(
                        t_d2 - t_d1)
            else:
                self._state, out = fn(self.state, inputs, label, lr, scale)
        self.optimizer._step_count += 1
        self._host_step += 1
        if self._sentinel_active:
            loss, finite, bad_idx = out
            self._sentinel_host_update(finite, bad_idx, scaler)
        else:
            loss = out
        from ..testing.faults import active_plan as _fault_plan
        if _fault_plan() is not None:
            from ..testing.faults import step_hook
            step_hook(self._host_step)
        return Tensor(loss)

    # -- sentinel host side ---------------------------------------------------
    def _sentinel_host_update(self, finite, bad_idx, scaler):
        """Per-step bookkeeping for the in-graph sentinel: skipped-step
        gauge, GradScaler backoff, and the bounded consecutive-bad-step
        abort with a diagnostic dump."""
        from ..utils.monitor import stat_add
        if bool(finite):            # one scalar device→host read per step
            self._bad_streak = 0
            if scaler is not None:
                scaler.on_step_result(False)
            return
        stat_add("train_skipped_steps")
        self._bad_streak += 1
        if scaler is not None:
            scaler.on_step_result(True)   # decr-on-nan backoff
        bad_name = self._sentinel_names[int(bad_idx)]
        limit = int(_flags.flag("sentinel_max_bad_steps"))
        if self._bad_streak < limit:
            return
        info = self._dump_sentinel_abort(bad_name, scaler)
        raise FloatingPointError(
            f"numerics sentinel: {self._bad_streak} consecutive non-finite "
            f"train steps (limit FLAGS_sentinel_max_bad_steps={limit}); "
            f"first non-finite tensor this step: {bad_name!r} at step "
            f"{self._host_step}; last good checkpoint: "
            f"{info.get('last_good_checkpoint')}")

    def _dump_sentinel_abort(self, bad_name, scaler):
        """Diagnostic dump next to the checkpoints (or PADDLE_TPU_DIAG_DIR)
        so the post-mortem has which tensor, which step, and where to
        resume from."""
        import json
        import os
        last_good = None
        if self.checkpoint_manager is not None:
            s = self.checkpoint_manager.latest_step()
            if s is not None:
                from ..checkpoint.manager import _step_dirname
                last_good = os.path.join(self.checkpoint_manager.root,
                                         _step_dirname(s))
        info = {"step": self._host_step, "bad_tensor": bad_name,
                "consecutive_bad_steps": self._bad_streak,
                "loss_scale": (scaler.get_loss_scaling()
                               if scaler is not None else None),
                "last_good_checkpoint": last_good, "wall": time.time()}
        dump_dir = (self.checkpoint_manager.root
                    if self.checkpoint_manager is not None
                    else os.environ.get("PADDLE_TPU_DIAG_DIR", ""))
        if dump_dir:
            try:
                from ..checkpoint.atomic import atomic_write_bytes
                atomic_write_bytes(
                    os.path.join(dump_dir, "sentinel_abort.json"),
                    json.dumps(info, indent=1).encode())
            except OSError:
                pass                    # the raise must not be masked
        return info

    # -- checkpoint hooks -----------------------------------------------------
    def attach_checkpoint_manager(self, manager):
        """Bind a ``checkpoint.CheckpointManager``: save_checkpoint /
        restore_from_checkpoint use it by default and the sentinel's
        abort dump can name the last good checkpoint."""
        self.checkpoint_manager = manager
        return manager

    def save_checkpoint(self, manager=None, wait=False):
        """Atomically checkpoint the compiled state at its current step
        (params + buffers + optimizer accumulators + step counter).
        Returns the step number saved."""
        m = manager or self.checkpoint_manager
        if m is None:
            raise ValueError("no CheckpointManager attached or passed")
        step_no = int(self.state["step"])
        payload = {"params": self.state["params"],
                   "buffers": self.state["buffers"],
                   "opt": self.state["opt"],
                   "step": np.asarray(step_no, np.int64)}
        for tag in ("dgc_u", "dgc_v"):  # engine-mode extras ride along
            if tag in self.state:
                payload[tag] = self.state[tag]
        m.save(step_no, payload, wait=wait)
        return step_no

    def restore_from_checkpoint(self, manager=None, step=None):
        """Restore params/buffers/opt/step from the newest complete (or
        an explicit ``step``) checkpoint, placing every leaf back under
        its compiled sharding.  Returns the restored step number."""
        m = manager or self.checkpoint_manager
        if m is None:
            raise ValueError("no CheckpointManager attached or passed")
        step_no, payload = m.load(step=step, return_numpy=True)
        self.state                      # materialize shardings
        sh = self._shardings
        self._state = {
            "params": {n: _global_put(np.asarray(v), sh["params"][n])
                       for n, v in payload["params"].items()},
            "buffers": {n: _global_put(np.asarray(v), sh["buffers"][n])
                        for n, v in payload["buffers"].items()},
            "opt": {s: {n: _global_put(np.asarray(v), sh["opt"][s][n])
                        for n, v in acc.items()}
                    for s, acc in payload["opt"].items()},
            "step": _global_put(np.asarray(int(payload["step"]), np.int32),
                                sh["step"]),
        }
        for tag in ("dgc_u", "dgc_v"):  # engine-mode extras ride along
            if tag in payload:
                self._state[tag] = {
                    n: _global_put(np.asarray(v), sh[tag][n])
                    for n, v in payload[tag].items()}
        self.optimizer._step_count = int(payload["step"])
        self._host_step = int(payload["step"])
        self._bad_streak = 0
        return step_no

    def sync_to_layer(self):
        """Write compiled-state params/buffers back into the eager Layer and
        optimizer accumulators (for save/eval interop)."""
        params, buffers, opt = (self.state["params"], self.state["buffers"],
                                self.state["opt"])
        if self._localsgd_degree() > 1:
            # collapse per-rank replicas: mean is exact right after a sync
            # step and the consensus answer between syncs
            fold = lambda v: (jnp.mean(v, axis=0)
                              if jnp.issubdtype(v.dtype, jnp.floating)
                              else v[0])
            params = {n: fold(v) for n, v in params.items()}
            buffers = {n: fold(v) for n, v in buffers.items()}
            opt = {s: {n: fold(v) for n, v in acc.items()}
                   for s, acc in opt.items()}
        if self._pipe is not None:
            self._pipe.load_flat_state(params, buffers)
        else:
            F.load_layer_state(self.layer, params, buffers)
        self.optimizer.adopt_functional_state(opt)
        self.optimizer._step_count = int(self.state["step"])


class EvalStep:
    """Jitted, sharded forward pass for evaluation/prediction.

    Uses the same mesh machinery as TrainStep (VERDICT r4 weak #5): params
    are placed once under their PartitionSpec shardings and kept
    device-resident across calls (``invalidate()`` re-reads the eager
    layer after external mutation — sync_to_layer / set_state_dict); the
    batch shards over dp like the training feed."""

    def __init__(self, layer, *, mesh=None, loss_fn=None):
        self.layer = layer
        self.mesh = mesh or get_mesh()
        self.loss_fn = _wrap_loss(loss_fn) if loss_fn is not None else None
        self._compiled = None
        self._state = None

    def invalidate(self):
        """Drop the device-resident param snapshot (call after mutating
        the eager layer's weights)."""
        self._state = None

    def _placed_state(self):
        if self._state is None:
            params, buffers = F.layer_state(self.layer)
            shardings = named_shardings(self.layer, self.mesh)
            rep = NamedSharding(self.mesh, P())
            params = {n: _global_put(v, shardings.get(n, rep))
                      for n, v in params.items()}
            buffers = {n: _global_put(v, rep) for n, v in buffers.items()}
            self._state = (params, buffers)
        return self._state

    def _build(self):
        def fwd(params, buffers, inputs, label):
            out = F.functional_call(self.layer, params, buffers, inputs,
                                    training=False)
            if self.loss_fn is not None and label is not None:
                first = out[0] if isinstance(out, (tuple, list)) else out
                return out, self.loss_fn(first, label)
            return out, None
        return jax.jit(fwd)

    def _put_batch(self, x):
        if x is None:
            return None
        dp = self.mesh.shape.get(DP_AXIS, 1)
        if x.ndim >= 1 and dp > 1 and x.shape[0] % dp == 0:
            return jax.device_put(x, batch_sharding(self.mesh, ndim=x.ndim))
        return x

    def __call__(self, inputs, label=None):
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        inputs = tuple(self._put_batch(_as_array(x)) for x in inputs)
        params, buffers = self._placed_state()
        if self._compiled is None:
            self._compiled = self._build()
        out, loss = self._compiled(params, buffers, inputs,
                                   None if label is None else _as_array(label))
        wrap = lambda o: Tensor(o) if o is not None else None
        if isinstance(out, (tuple, list)):
            out = type(out)(Tensor(o) for o in out)
        else:
            out = Tensor(out)
        return (out, wrap(loss)) if self.loss_fn is not None else out
