"""``paddle.fluid`` compatibility namespace.

Reference parity: python/paddle/fluid/ — the 1.x-era API surface fluid
user code imports (``import paddle.fluid as fluid``).  Every name aliases
the modern seat of the same capability (static Program/Executor, the 2.0
layers/optimizers, the dygraph guard), so fluid-era scripts run against
the TPU engine without a rewrite.  New code should import the 2.0
surfaces directly.
"""
from __future__ import annotations

# -- core static-graph objects (fluid/framework.py, fluid/executor.py) -------
from ..static import (  # noqa: F401
    Program, Variable, program_guard, default_main_program,
    default_startup_program, Executor, Scope, global_scope, scope_guard,
    CompiledProgram, BuildStrategy, ExecutionStrategy,
    save_inference_model, load_inference_model,
)
from ..static import data  # noqa: F401
from ..framework import core  # noqa: F401

# -- fluid.layers: the graph-building DSL (fluid/layers/) ---------------------
from ..static import nn as layers  # noqa: F401

# -- fluid.dygraph (fluid/dygraph/) -------------------------------------------
from .. import jit as dygraph_jit  # noqa: F401


class _DygraphNS:
    """fluid.dygraph namespace: guard() + to_variable + the jit entries."""

    @staticmethod
    def guard(place=None):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            from ..framework import core as _core
            with _core.dygraph_mode_guard():
                yield
        return _guard()

    @staticmethod
    def to_variable(value, name=None, zero_copy=None):
        from .. import to_tensor
        return to_tensor(value)

    from ..jit import TranslatedLayer  # noqa: F401


dygraph = _DygraphNS()

# -- fluid.optimizer (fluid/optimizer.py: *Optimizer spellings) ---------------
from .. import optimizer as _opt  # noqa: E402

SGDOptimizer = _opt.SGD
MomentumOptimizer = _opt.Momentum
AdamOptimizer = _opt.Adam
AdamaxOptimizer = _opt.Adamax
AdagradOptimizer = _opt.Adagrad
AdadeltaOptimizer = _opt.Adadelta
RMSPropOptimizer = _opt.RMSProp
LambOptimizer = _opt.Lamb
optimizer = _opt

# -- fluid.initializer / fluid.regularizer / fluid.clip -----------------------
from ..nn import initializer  # noqa: F401
from .. import regularizer  # noqa: F401
from ..nn.clip import (  # noqa: F401
    ClipGradByValue as GradientClipByValue,
    ClipGradByNorm as GradientClipByNorm,
    ClipGradByGlobalNorm as GradientClipByGlobalNorm,
)

# -- fluid.io (fluid/io.py) ---------------------------------------------------
from ..static import io  # noqa: F401

# -- misc fluid toplevel ------------------------------------------------------
from ..framework import CPUPlace, CUDAPlace  # noqa: F401


def CUDAPinnedPlace():  # noqa: N802 — fluid spelling
    return CPUPlace()


def is_compiled_with_cuda():
    return False


from ..framework.tensor import Tensor as LoDTensor  # noqa: E402,F401
from .. import create_lod_tensor  # noqa: E402,F401


def enable_dygraph(place=None):
    from .. import disable_static
    disable_static()


def disable_dygraph():
    from .. import enable_static
    enable_static()


def in_dygraph_mode():
    from ..framework import core as _core
    return not _core.in_static_mode()
