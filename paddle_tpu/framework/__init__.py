"""Framework core: tensor, autograd, primitives, device, dtype, flags, rng."""
from . import core
from .core import (  # noqa: F401
    in_dygraph_mode, in_static_mode, enable_static, disable_static,
    no_grad_guard, set_grad_enabled,
)
from .dtype import (  # noqa: F401
    bfloat16, float16, float32, float64, int8, int16, int32, int64, uint8,
    bool_, complex64, convert_dtype, set_default_dtype, get_default_dtype,
)
from .flags import (  # noqa: F401
    set_flags, get_flags, define_flag, flag, flags_snapshot, flags_restore,
)
from .place import (  # noqa: F401
    Place, CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
    set_device, get_device, current_place, device_count,
    is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu,
)
from .random import seed, get_rng_state, set_rng_state, default_generator  # noqa: F401
from .tensor import Tensor, Parameter, to_tensor, unwrap, wrap  # noqa: F401
from .autograd import grad, run_backward  # noqa: F401
from .primitive import Primitive, primitive, get_primitive, all_primitives  # noqa: F401
from . import enforce  # noqa: F401
from .enforce import (  # noqa: F401
    EnforceNotMet, InvalidArgumentError, NotFoundError, OutOfRangeError,
    AlreadyExistsError, ResourceExhaustedError, PreconditionNotMetError,
    PermissionDeniedError, ExecutionTimeoutError, UnimplementedError,
    UnavailableError, FatalError, ExternalError,
)

# register the static-randomness primitive at import so deserialized
# programs containing key_advance ops resolve it in any fresh process
from .random import register_key_advance as _rka  # noqa: E402
_rka()
del _rka
