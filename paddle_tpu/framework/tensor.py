"""Eager Tensor (the VarBase equivalent) over a jax.Array.

Reference parity: paddle/fluid/imperative/layer.h:65 (VarBase) +
python/paddle/fluid/dygraph/varbase_patch_methods.py (backward at :135) and
math_op_patch.py. TPU-first: the buffer is a PJRT-owned jax.Array, so device
placement, async dispatch and donation are XLA's problem; the Tensor adds
Paddle semantics -- ``stop_gradient`` (default True), ``.grad`` accumulation,
``persistable``, name -- and the tape hook for the autograd engine.

Operator methods (``__add__``, ``reshape``...) are patched on by
``paddle_tpu.ops`` at import, mirroring math_op_patch.py's monkey-patching.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import core
from .dtype import convert_dtype, get_default_dtype

_name_counter = [0]


def _auto_name(prefix="tmp"):
    _name_counter[0] += 1
    return f"{prefix}_{_name_counter[0]}"


class LoDArray(np.ndarray):
    """numpy carrier for LoD offsets: survives pickling through DataLoader
    worker queues without touching jax in forked children; converting to a
    Tensor lifts ``.lod`` onto the tensor (lod_tensor.h parity)."""

    lod = None

    @classmethod
    def wrap(cls, arr, lod):
        out = np.asarray(arr).view(cls)
        out.lod = [list(int(o) for o in level) for level in lod]
        return out

    def __reduce__(self):
        base = super().__reduce__()
        return (base[0], base[1], base[2] + (self.lod,))

    def __setstate__(self, state):
        self.lod = state[-1]
        super().__setstate__(state[:-1])


def pad_ragged_rows(rows):
    """Rows of shape (L_i, ...) → LoDArray (B, max L, ...) with level-1
    offsets. The one shared pad-and-offset implementation behind
    create_lod_tensor and DataLoader ragged collate."""
    rows = [np.asarray(r) for r in rows]
    lens = [r.shape[0] for r in rows]
    m = max(lens) if lens else 0
    feat = rows[0].shape[1:] if rows else ()
    pad = np.zeros((len(rows), m) + feat, rows[0].dtype if rows else np.float32)
    for i, r in enumerate(rows):
        pad[i, :r.shape[0]] = r
    offs = [0]
    for L in lens:
        offs.append(offs[-1] + L)
    return LoDArray.wrap(pad, [offs])


class Tensor:
    __slots__ = ("_value", "stop_gradient", "persistable", "name", "grad",
                 "_node", "_out_index", "_retain_grads", "_hooks", "is_leaf",
                 "_bwd_done", "_version", "_consumers", "_consumers_cap",
                 "_lod", "_conv_epilogue", "_bn_act_upgrade", "__weakref__")

    def __init__(self, value, stop_gradient=True, name=None, persistable=False):
        # capture LoD BEFORE coercion: jnp.asarray strips LoDArray attrs
        lod = getattr(value, "lod", None) \
            if not isinstance(value, jax.Array) else None
        if isinstance(value, Tensor):
            value = value._value
        if not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.name = name or _auto_name()
        self.grad = None
        self._node = None
        self._out_index = 0
        self._retain_grads = False
        self._version = 0      # bumped by in-place mutation (version check)
        self._consumers = None  # weakrefs to GradNodes holding a LEAF edge
        self._consumers_cap = 16  # amortized dead-ref compaction threshold
        self._hooks = []
        self.is_leaf = True
        self._bwd_done = False
        # LoD carrier (lod_tensor.h): [[offsets...], ...]; lifted from a
        # LoDArray (ragged DataLoader batch) when one is converted
        self._lod = [list(level) for level in lod] if lod else None

    # -- LoD (lod_tensor.h parity: raggedness rides ON the tensor) -----------
    @property
    def lod(self):
        """Level-of-detail offsets, e.g. [[0, 2, 5]] for rows of len 2, 3.
        None for dense tensors. The TPU data layout is padded
        [batch, max_len, ...]; sequence primitives read the offsets when no
        explicit lengths are passed (sequence_ops/ + lod_tensor.h)."""
        return self._lod

    def set_lod(self, lod):
        self._lod = [list(int(o) for o in level) for level in lod] \
            if lod else None

    def recursive_sequence_lengths(self):
        """Offsets → per-sequence lengths per level (LoDTensor API)."""
        if self._lod is None:
            return []
        return [[level[i + 1] - level[i] for i in range(len(level) - 1)]
                for level in self._lod]

    def has_valid_recursive_sequence_lengths(self):
        if self._lod is None:
            return True
        for level in self._lod:
            if not level or level[0] != 0 or \
                    any(level[i] > level[i + 1]
                        for i in range(len(level) - 1)):
                return False
        return True

    def seq_lengths(self):
        """Finest-level lengths as an array, or None (the form the masked
        dense sequence ops consume)."""
        if self._lod is None:
            return None
        level = self._lod[-1]
        return jnp.asarray([level[i + 1] - level[i]
                            for i in range(len(level) - 1)], jnp.int32)

    # -- structural ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from . import place as place_mod
        return place_mod.current_place()

    @property
    def T(self):
        from .. import ops
        return ops.transpose(self, list(range(self.ndim))[::-1])

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_str = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_str},\n       {np.array2string(self.numpy(), prefix='       ')})")

    def __bool__(self):
        if self.size != 1:
            raise ValueError("truth value of multi-element Tensor is ambiguous")
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __hash__(self):
        return id(self)

    def __dlpack__(self, **kw):
        return self._value.__dlpack__(**kw)

    # -- autograd ------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        """varbase_patch_methods.py:135 -> BasicEngine parity."""
        from .autograd import run_backward
        run_backward(self, grad_tensor, retain_graph)

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        self.is_leaf = True
        return self

    def clone(self):
        from .. import ops
        return ops.assign(self)

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook):
        """Grad hook parity (imperative VariableWrapper hooks)."""
        self._hooks.append(hook)

        class _Removable:
            def remove(_self):
                if hook in self._hooks:
                    self._hooks.remove(hook)
        return _Removable()

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        return self

    # -- in-place-ish value plumbing (Paddle exposes set_value on params) -----
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch {value.shape} vs {self._value.shape}")
        self._value = value
        self._version += 1    # off-tape mutation: backward through a
        return self           # pre-mutation consumer must raise

    def get_tensor(self):
        return self

    def value(self):
        return self

    def block_until_ready(self):
        jax.block_until_ready(self._value)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def to(self, *args, **kwargs):
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a.lower() in ("cpu", "tpu", "gpu"):
                continue
            dtype = a
        if dtype is not None:
            return self.astype(dtype)
        return self

    def cpu(self):
        return Tensor(jax.device_get(self._value), stop_gradient=self.stop_gradient)

    def cuda(self):
        return self

    def pin_memory(self):
        return self

    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    # requires-grad compatibility helpers
    @property
    def requires_grad(self):
        return not self.stop_gradient

    @requires_grad.setter
    def requires_grad(self, v):
        self.stop_gradient = not v


class Parameter(Tensor):
    """framework.py:5311 (ParamBase) parity: trainable persistable tensor."""
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "_partition_spec", "_autoshard_rule")

    def __init__(self, value, name=None, trainable=True, regularizer=None,
                 need_clip=True):
        super().__init__(value, stop_gradient=not trainable, name=name or _auto_name("param"),
                         persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = regularizer
        self.need_clip = need_clip

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        value = data._value
        if dtype is not None:
            value = value.astype(convert_dtype(dtype))
        return Tensor(value, stop_gradient=stop_gradient)
    if isinstance(data, jax.Array):
        arr = data if dtype is None else data.astype(convert_dtype(dtype))
        return Tensor(arr, stop_gradient=stop_gradient)
    arr = np.asarray(data)
    if dtype is not None:
        arr = arr.astype(convert_dtype(dtype))
    elif arr.dtype == np.float64:
        arr = arr.astype(get_default_dtype())
    out = Tensor(jnp.asarray(arr), stop_gradient=stop_gradient)
    lod = getattr(data, "lod", None)     # LoDArray: raggedness survives
    if lod:
        out.set_lod(lod)
    return out


def unwrap(x):
    """Tensor|array|scalar -> jax-compatible value."""
    return x._value if isinstance(x, Tensor) else x


def wrap(value, stop_gradient=True):
    return Tensor(value, stop_gradient=stop_gradient)
