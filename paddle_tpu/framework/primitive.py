"""Primitive op machinery: registry, jitted dispatch, cached VJPs.

Reference parity: this is the TPU replacement for the whole
OperatorWithKernel::RunImpl pipeline (paddle/fluid/framework/operator.cc:1093)
plus the op registry (op_registry.h:256) and the dygraph PreparedOp cache
(imperative/prepared_operator.cc). Where Paddle dispatches a hand-written
CUDA/Eigen kernel per OpKernelType, here every primitive is a pure jax function
lowered by XLA:TPU; "kernel choice" collapses to one jit cache keyed by
(op, static attrs) with shape/dtype specialization handled by jax.jit itself.

Backward: instead of registering a grad op per forward op (GradOpMaker), each
primitive's VJP is derived by jax.vjp and jitted once per (op, attrs, shapes).
Ops that need custom gradients (e.g. Pallas kernels) use jax.custom_vjp inside
their ``fn`` -- the tape machinery is agnostic.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from . import core
from .flags import flag
from .autograd import GradNode
from .tensor import Tensor

_PRIMS: Dict[str, "Primitive"] = {}


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    import numpy as np
    if isinstance(v, np.dtype):
        return str(v)
    return v


def _attrs_key(attrs):
    return tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))


class Primitive:
    """A registered op: pure jax fn (*arrays, **static_attrs) -> array|tuple."""

    def __init__(self, name: str, fn: Callable, multi_output: bool = False,
                 differentiable: bool = True):
        self.name = name
        self.fn = fn
        self.multi_output = multi_output
        self.differentiable = differentiable
        self._fwd_cache: Dict = {}
        self._bwd_cache: Dict = {}
        _PRIMS[name] = self

    # -- compiled callables --------------------------------------------------
    def _fwd(self, key, attrs):
        f = self._fwd_cache.get(key)
        if f is None:
            base = functools.partial(self.fn, **attrs) if attrs else self.fn
            f = jax.jit(base)
            self._fwd_cache[key] = f
        return f

    def _bwd(self, key, attrs):
        f = self._bwd_cache.get(key)
        if f is None:
            base = functools.partial(self.fn, **attrs) if attrs else self.fn
            multi = self.multi_output

            def backward(cts, *primals):
                _, vjp = jax.vjp(base, *primals)
                return vjp(cts if multi else cts[0])

            f = jax.jit(backward)
            self._bwd_cache[key] = f
        return f

    # -- eager application ---------------------------------------------------
    def __call__(self, *args, **attrs):
        arrs = tuple(a._value if isinstance(a, Tensor) else a for a in args)
        key = _attrs_key(attrs)
        out = self._fwd(key, attrs)(*arrs)

        if flag("benchmark"):
            jax.block_until_ready(out)
        if flag("check_nan_inf"):
            _check_finite(self.name, out)

        needs_grad = self.differentiable and core.grad_enabled() and any(
            isinstance(a, Tensor) and not a.stop_gradient for a in args)

        outs = out if self.multi_output else (out,)
        tensors = tuple(Tensor(o, stop_gradient=not needs_grad) for o in outs)

        if needs_grad:
            node = GradNode(
                self.name, self._bwd(key, attrs), arrs,
                tuple(a if isinstance(a, Tensor) else None for a in args),
                [(o.shape, o.dtype) for o in outs])
            for i, t in enumerate(tensors):
                t._node = node
                t._out_index = i
                t.is_leaf = False
        return tensors if self.multi_output else tensors[0]

    # raw (no tape, no wrap): used by static executor / jit tracer
    def raw(self, *arrs, **attrs):
        return self._fwd(_attrs_key(attrs), attrs)(*arrs)


def _check_finite(name, out):
    """FLAGS_check_nan_inf parity (details/nan_inf_utils_detail.cc:301)."""
    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            if not bool(jnp.all(jnp.isfinite(leaf))):
                raise FloatingPointError(
                    f"Operator {name} output contains NaN/Inf "
                    f"(FLAGS_check_nan_inf)")


def primitive(name: str, multi_output: bool = False, differentiable: bool = True):
    """Decorator: register a pure jax function as a framework primitive."""
    def deco(fn):
        return Primitive(name, fn, multi_output=multi_output,
                         differentiable=differentiable)
    return deco


def get_primitive(name: str) -> Primitive:
    return _PRIMS[name]


def all_primitives():
    return dict(_PRIMS)
