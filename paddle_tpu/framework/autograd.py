"""Define-by-run autograd engine (tape).

Reference parity: paddle/fluid/imperative/basic_engine.cc -- ``Init`` (:39)
seeds the root cotangent, ``PrepareDeps`` (:154) BFS-counts grad-node
dependencies, ``Execute`` (:191) runs a ready-queue of grad nodes with
``GradientAccumulator`` summing multi-consumer grads. Double grad
(partial_grad_engine.cc) is exposed via :func:`grad`.

TPU-first: each tape node's backward is a *cached jitted XLA computation*
(built once per op+shape via jax.vjp), so eager backward dispatches compiled
kernels instead of interpreting -- the analogue of PreparedOp kernel caching
(prepared_operator.cc).
"""
from __future__ import annotations

from collections import deque, OrderedDict
from typing import Optional

import jax
import weakref

import jax.numpy as jnp

from .tensor import Tensor

_float0 = jax.dtypes.float0


class GradNode:
    """One recorded op application: knows how to map out-cotangents to in-cotangents."""
    __slots__ = ("name", "grad_fn", "primals", "inputs", "input_edges",
                 "out_avals", "out_ct", "visited_tag", "__weakref__")

    def __init__(self, name, grad_fn, primals, inputs, out_avals):
        self.name = name
        self.grad_fn = grad_fn        # (cts_tuple, *primals) -> tuple of input cts
        self.primals = primals        # tuple of jax arrays (residual-free: replayed)
        self.inputs = inputs          # tuple of Tensor refs aligned with primals
        # graph edges captured at RECORD time: an in-place op re-pointing a
        # consumed Tensor's _node later must not reroute this op's backward
        # (the version-counter problem; basic_engine resolves edges eagerly
        # too)
        self.input_edges = tuple(
            (t._node, t._out_index, t._version) if isinstance(t, Tensor)
            else (None, None, 0)
            for t in inputs)
        # consumer back-edges, LEAF edges only: backward's in-place version
        # check reads the edge version solely on (None, ·) edges, so only
        # nodes holding a leaf edge to a tensor can ever need a re-stamp
        # by an in-place op (_adopt).  Dead refs are compacted amortized
        # (cap doubles on live size) so long runs don't leak weakrefs.
        ref = weakref.ref(self)
        for t in inputs:
            if isinstance(t, Tensor) and t._node is None:
                lst = t._consumers
                if lst is None:
                    lst = t._consumers = []
                lst.append(ref)
                if len(lst) >= t._consumers_cap:
                    live = [r for r in lst if r() is not None]
                    t._consumers = live
                    t._consumers_cap = max(2 * len(live), 16)
        self.out_avals = out_avals    # list[(shape, dtype)] per output
        self.out_ct = None
        self.visited_tag = 0

    def seed(self, index, ct):
        if self.out_ct is None:
            self.out_ct = [None] * len(self.out_avals)
        # dtype coercion: AMP casts at op dispatch are not part of any
        # recorded vjp, so a downstream node may hand back a cotangent in a
        # different precision than this node's output (fp32 ct for a bf16
        # out); align to the recorded output dtype
        dtype = self.out_avals[index][1]
        if hasattr(ct, "dtype") and ct.dtype != dtype and \
                ct.dtype != _float0:
            ct = ct.astype(dtype)
        cur = self.out_ct[index]
        self.out_ct[index] = ct if cur is None else cur + ct

    def materialize_cts(self):
        cts = []
        for i, (shape, dtype) in enumerate(self.out_avals):
            ct = None if self.out_ct is None else self.out_ct[i]
            if ct is None:
                ct = jnp.zeros(shape, dtype)
            cts.append(ct)
        return tuple(cts)

    def release(self):
        self.primals = None
        self.inputs = None
        self.input_edges = None
        self.out_ct = None
        self.grad_fn = None


_tag_counter = [0]


def _accumulate_into_tensor(t: Tensor, ct):
    from .selected_rows import SelectedRows
    if isinstance(ct, SelectedRows):
        # sparse accumulation (GradientAccumulator's SelectedRows branch,
        # imperative/gradient_accumulator.cc): sparse+sparse concatenates,
        # sparse+dense densifies.  Grad hooks see the SelectedRows itself
        # (a hook may return a replacement — SelectedRows or dense).
        for hook in t._hooks:
            out = hook(ct)
            if out is not None:
                ct = out
        if not isinstance(ct, SelectedRows):
            ct = ct._value if isinstance(ct, Tensor) else ct
            t.grad = Tensor(ct, stop_gradient=True) if t.grad is None \
                else Tensor(t.grad._value + ct, stop_gradient=True)
            return
        if t.grad is None:
            t.grad = ct
        elif isinstance(t.grad, SelectedRows):
            t.grad = t.grad + ct
        else:
            t.grad = Tensor(t.grad._value + ct.to_dense(),
                            stop_gradient=True, name=t.name + "@GRAD")
        return
    if isinstance(t.grad, SelectedRows):
        t.grad = Tensor(t.grad.to_dense() + ct, stop_gradient=True,
                        name=t.name + "@GRAD")
        return
    if ct.dtype == _float0:
        return
    for hook in t._hooks:
        out = hook(Tensor(ct, stop_gradient=True))
        if out is not None:
            ct = out._value if isinstance(out, Tensor) else out
    if t.grad is None:
        t.grad = Tensor(ct, stop_gradient=True, name=t.name + "@GRAD")
    else:
        t.grad = Tensor(t.grad._value + ct, stop_gradient=True,
                        name=t.name + "@GRAD")


def run_backward(root: Tensor, grad_tensor: Optional[Tensor] = None,
                 retain_graph: bool = False):
    """basic_engine.cc:39 Init + :191 Execute."""
    if root.stop_gradient:
        raise RuntimeError(
            f"Tensor {root.name} has stop_gradient=True; cannot backward")
    if grad_tensor is None:
        if root.size != 1:
            raise RuntimeError("grad_tensor must be given for non-scalar backward "
                               "(loss must be a scalar)")
        seed_ct = jnp.ones(root._value.shape, root._value.dtype)
    else:
        seed_ct = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    node = root._node
    if node is None:
        _accumulate_into_tensor(root, seed_ct)
        return

    # PrepareDeps (basic_engine.cc:154): count consumer edges per reachable node
    _tag_counter[0] += 1
    tag = _tag_counter[0]
    deps = {}
    stack = [node]
    node.visited_tag = tag
    order = []
    while stack:
        n = stack.pop()
        order.append(n)
        for (p, _, _) in n.input_edges:
            if p is None:
                continue
            deps[id(p)] = deps.get(id(p), 0) + 1
            if p.visited_tag != tag:
                p.visited_tag = tag
                stack.append(p)

    node.seed(root._out_index, seed_ct)
    queue = deque([node])
    processed = []
    while queue:
        n = queue.popleft()
        processed.append(n)
        cts = n.materialize_cts()
        in_cts = n.grad_fn(cts, *n.primals)
        for t, (p, out_idx, ver), ct in zip(n.inputs, n.input_edges,
                                            in_cts):
            if not isinstance(t, Tensor):
                continue
            zero_ct = ct.dtype == _float0
            if p is not None:
                # deps bookkeeping runs even for float0 cotangents (int
                # outputs): skipping it would starve the parent node and
                # silently drop its OTHER edges' real gradients
                if not zero_ct:
                    p.seed(out_idx, ct)
                    if t._retain_grads and not t.stop_gradient:
                        _accumulate_into_tensor(t, ct)
                deps[id(p)] -= 1
                if deps[id(p)] == 0:
                    queue.append(p)
            elif not zero_ct and not t.stop_gradient:
                # ver None = edge exempted by _adopt: the op is part of the
                # tensor's own in-place lineage (its primals captured the
                # value it consumed, so replay is always valid)
                if ver is not None and t._version != ver:
                    raise RuntimeError(
                        f"leaf Tensor {t.name} was modified by an in-place "
                        f"operation after being consumed by {n.name}; "
                        f"gradients would apply to a stale version "
                        f"(version {ver} vs {t._version})")
                _accumulate_into_tensor(t, ct)
        if not retain_graph:
            n.release()
    if not retain_graph:
        root._node = None
    root._bwd_done = True


# ---------------------------------------------------------------------------
# Double grad (create_graph=True): a *recording* backward pass.  Instead of
# running each node's jitted grad_fn on raw arrays, the backward computation
# itself is applied through the tape — cotangents are Tensors, each node
# application records a new GradNode whose grad_fn is jax.vjp of the first
# backward.  The returned gradients therefore carry a live autograd graph and
# can be differentiated again (PartialGradEngine / partial_grad_engine.cc
# ``create_graph`` parity).  Known limitation: AMP autocast inside the first
# forward is replayed at the original input dtypes, so mixing auto_cast with
# double grad is unsupported.
# ---------------------------------------------------------------------------

# Bounded LRU: keyed (id(grad_fn), n_cts) with a strong ref to grad_fn held
# *while the entry lives* (pins the id against recycling).  Eviction drops
# both the wrapper and the ref, so long double-grad sessions can't grow it
# without bound; an evicted-then-recycled id simply re-caches.
_SECOND_ORDER_CACHE_CAP = 256
_second_order_cache: OrderedDict = OrderedDict()


def _so_cache_get(key):
    hit = _second_order_cache.get(key)
    if hit is not None:
        _second_order_cache.move_to_end(key)
    return hit


def _so_cache_put(key, value):
    _second_order_cache[key] = value
    _second_order_cache.move_to_end(key)
    while len(_second_order_cache) > _SECOND_ORDER_CACHE_CAP:
        _second_order_cache.popitem(last=False)


def _recorded_grad_apply(n: GradNode):
    """Apply node n's grad_fn with Tensor cotangents, recording the result."""
    import numpy as np
    n_cts = len(n.out_avals)

    cts = []
    for i, (shape, dtype) in enumerate(n.out_avals):
        ct = None if n.out_ct is None else n.out_ct[i]
        if ct is None:
            ct = Tensor(jnp.zeros(shape, dtype), stop_gradient=True)
        cts.append(ct)

    args = list(cts)
    for i, t in enumerate(n.inputs):
        args.append(t if isinstance(t, Tensor) else n.primals[i])

    grad_fn = n.grad_fn
    key = (id(grad_fn), n_cts)
    hit = _so_cache_get(key)
    if hit is None:
        def flat(*a, _g=grad_fn, _n=n_cts):
            return _g(tuple(a[:_n]), *a[_n:])
        _so_cache_put(key, (flat, grad_fn))
    else:
        flat = hit[0]

    arrs = tuple(a._value if isinstance(a, Tensor) else a for a in args)
    outs = flat(*arrs)

    from . import core
    needs = core.grad_enabled() and any(
        isinstance(a, Tensor) and not a.stop_gradient for a in args)
    tensors = []
    rec_idx = []           # output slots that participate in the new node
    for i, o in enumerate(outs):
        sg = (not needs) or o.dtype == _float0
        tensors.append(Tensor(o, stop_gradient=sg))
        if not sg:
            rec_idx.append(i)
    if needs and rec_idx:
        node = GradNode(
            n.name + "_grad", None, arrs,
            tuple(a if isinstance(a, Tensor) else None for a in args),
            [(np.shape(o), o.dtype) for o in outs])

        def bwd(cts2, *primals, _flat=flat):
            _, vjp = jax.vjp(_flat, *primals)
            return vjp(cts2)
        node.grad_fn = bwd
        for i in rec_idx:
            t = tensors[i]
            t._node = node
            t._out_index = i
            t.is_leaf = False
    return tensors


def _seed_recorded(out_ct, index, aval, ct):
    """Tensor-valued GradNode.seed: accumulate via recorded add/cast ops."""
    dtype = aval[1]
    if ct._value.dtype != dtype and ct._value.dtype != _float0:
        ct = ct.astype(dtype) if hasattr(ct, "astype") else ct
    cur = out_ct[index]
    out_ct[index] = ct if cur is None else cur + ct


def _backward_recorded(root: Tensor, seed: Tensor, wanted, table,
                       retain_graph: bool):
    """run_backward twin where cotangents are Tensors on a live tape."""
    node = root._node
    if node is None:
        if id(root) in wanted:
            cur = table.get(id(root))
            table[id(root)] = seed if cur is None else cur + seed
        return

    _tag_counter[0] += 1
    tag = _tag_counter[0]
    deps = {}
    stack = [node]
    node.visited_tag = tag
    while stack:
        n = stack.pop()
        for (p, _, _) in n.input_edges:
            if p is None:
                continue
            deps[id(p)] = deps.get(id(p), 0) + 1
            if p.visited_tag != tag:
                p.visited_tag = tag
                stack.append(p)

    # Tensor-valued cotangent accumulation lives in a side dict so the
    # original nodes' out_ct slots stay array-typed for later plain backward
    out_cts = {id(node): [None] * len(node.out_avals)}
    _seed_recorded(out_cts[id(node)], root._out_index, node.out_avals[root._out_index], seed)
    queue = deque([node])
    while queue:
        n = queue.popleft()
        n.out_ct = out_cts.get(id(n))        # borrowed by _recorded_grad_apply
        in_cts = _recorded_grad_apply(n)
        n.out_ct = None
        for t, (p, out_idx, ver), ct in zip(n.inputs, n.input_edges,
                                            in_cts):
            if not isinstance(t, Tensor):
                continue
            zero_ct = ct._value.dtype == _float0
            if not zero_ct and id(t) in wanted:
                if p is None and ver is not None and t._version != ver:
                    raise RuntimeError(
                        f"leaf Tensor {t.name} was modified by an in-place "
                        f"operation after being consumed by {n.name} "
                        f"(version {ver} vs {t._version})")
                cur = table.get(id(t))
                table[id(t)] = ct if cur is None else cur + ct
            if p is not None:
                if not zero_ct:
                    slot = out_cts.get(id(p))
                    if slot is None:
                        slot = out_cts[id(p)] = [None] * len(p.out_avals)
                    _seed_recorded(slot, out_idx, p.out_avals[out_idx], ct)
                deps[id(p)] -= 1
                if deps[id(p)] == 0:
                    queue.append(p)
        if not retain_graph:
            n.release()
    if not retain_graph:
        root._node = None


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad parity (partial_grad_engine.cc).

    Returns grads of ``outputs`` w.r.t. ``inputs`` without touching ``.grad``
    slots. With ``create_graph=True`` the backward pass itself is recorded on
    the tape (each grad op's VJP derived by jax.vjp of the first backward), so
    the returned gradients can be differentiated again — double/higher-order
    grad.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and len(grad_outputs) != len(outputs):
        raise ValueError(
            f"grad_outputs has {len(grad_outputs)} entries but outputs has "
            f"{len(outputs)}; they must match (use None entries for "
            "default ones-like seeds)")
    if create_graph:
        retain = True if retain_graph is None else bool(retain_graph)
        table: dict = {}
        wanted = {id(t) for t in inputs}
        gos = grad_outputs or [None] * len(outputs)
        for o, go in zip(outputs, gos):
            if go is None:
                seed = Tensor(jnp.ones(o._value.shape, o._value.dtype),
                              stop_gradient=True)
            elif isinstance(go, Tensor):
                seed = go
            else:
                seed = Tensor(jnp.asarray(go), stop_gradient=True)
            _backward_recorded(o, seed, wanted, table, retain)
        results = []
        for t in inputs:
            g = table.get(id(t))
            if g is None and not allow_unused:
                raise RuntimeError(f"input {t.name} unused in graph "
                                   "(pass allow_unused=True to permit)")
            results.append(g)
        return results
    # run a private backward that records into a side table
    saved = [(t, t.grad, t._retain_grads, t.stop_gradient) for t in inputs]
    for t in inputs:
        t.grad = None
        t._retain_grads = True
        t.stop_gradient = False
    try:
        for o, go in zip(outputs, grad_outputs or [None] * len(outputs)):
            run_backward(o, go, retain_graph=bool(retain_graph))
        results = []
        for t in inputs:
            if t.grad is None and not allow_unused:
                raise RuntimeError(f"input {t.name} unused in graph "
                                   "(pass allow_unused=True to permit)")
            results.append(t.grad)
        return results
    finally:
        for t, g, r, sg in saved:
            t.grad = g
            t._retain_grads = r
            t.stop_gradient = sg
