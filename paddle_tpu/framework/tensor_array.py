"""Bounded traced tensor array — the list-append lowering for @to_static.

Reference parity: dygraph_to_static/list_transformer.py rewrites Python
list creation/append under traced control flow into LoDTensorArray ops
(create_array / array_write, operators/controlflow/).  The LoDTensorArray
grows dynamically; XLA programs cannot, so the TPU lowering is a FIXED
capacity buffer + live size counter (the same static-budget pattern as the
detection NMS ops) carried through lax.while_loop/cond as a pytree.
Appends beyond capacity set the ``ovf`` flag, which dy2static routes
through the fetched-assert channel so the overflow RAISES host-side after
the run (instead of silently overwriting the last slot) — raise the budget
with ``paddle.jit.set_tensor_array_capacity`` when a loop legitimately
collects more.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_TA_CAPACITY = [256]


def set_tensor_array_capacity(n: int) -> None:
    """Static element budget for lists converted under @to_static."""
    _TA_CAPACITY[0] = int(n)


def get_tensor_array_capacity() -> int:
    return _TA_CAPACITY[0]


class BoundedTensorArray:
    """Functional fixed-capacity list of uniformly-shaped tensors."""

    def __init__(self, buffer, size, ovf=None):
        self.buffer = buffer      # [capacity, *elem_shape]
        self.size = size          # scalar int32 (possibly traced)
        # overflow flag: set when an append lands on a full buffer; rides
        # the pytree so loop/cond carries keep it, and dy2static routes it
        # through the fetched-assert channel so overflow raises host-side
        # instead of silently overwriting the last slot
        self.ovf = jnp.asarray(False) if ovf is None else ovf

    @classmethod
    def empty_like_elem(cls, elem, capacity=None):
        cap = capacity or get_tensor_array_capacity()
        buf = jnp.zeros((cap,) + tuple(elem.shape), elem.dtype)
        return cls(buf, jnp.asarray(0, jnp.int32))

    @classmethod
    def from_list(cls, items, capacity=None):
        cap = capacity or get_tensor_array_capacity()
        stacked = jnp.stack(items)
        if stacked.shape[0] > cap:
            raise ValueError(
                f"list of {stacked.shape[0]} elements exceeds the tensor "
                f"array capacity {cap}; raise it with "
                "paddle.jit.set_tensor_array_capacity")
        pad = jnp.zeros((cap - stacked.shape[0],) + stacked.shape[1:],
                        stacked.dtype)
        return cls(jnp.concatenate([stacked, pad], axis=0),
                   jnp.asarray(stacked.shape[0], jnp.int32))

    @property
    def capacity(self):
        return self.buffer.shape[0]

    def append(self, x):
        x = jnp.asarray(x, self.buffer.dtype)
        idx = jnp.clip(self.size, 0, self.capacity - 1)
        buf = jax.lax.dynamic_update_index_in_dim(self.buffer, x, idx,
                                                  axis=0)
        # size saturates at capacity (length() stays truthful about how
        # many elements the buffer holds); the overflow flag records that
        # an append exceeded the budget so it raises host-side instead of
        # passing as a silent last-slot overwrite
        ovf = jnp.logical_or(self.ovf, self.size >= self.capacity)
        return BoundedTensorArray(
            buf, jnp.minimum(self.size + 1, self.capacity), ovf)

    def __getitem__(self, i):
        if hasattr(i, "_value"):      # framework Tensor index
            i = i._value
        i = jnp.asarray(i, jnp.int32)
        # Python list semantics: negative indexes count from the LIVE size
        i = jnp.where(i < 0, self.size + i, i)
        out = jax.lax.dynamic_index_in_dim(self.buffer, i, axis=0,
                                           keepdims=False)
        from .tensor import Tensor
        return Tensor(out)

    def length(self):
        return self.size

    def stack(self):
        """Full [capacity, ...] buffer; valid prefix is [:length()]."""
        return self.buffer

    def concat(self):
        """Elements joined along their leading dim (list-concat
        semantics); valid prefix is [:length()*elem_dim0]."""
        b = self.buffer
        return b.reshape((b.shape[0] * b.shape[1],) + b.shape[2:]) \
            if b.ndim > 1 else b


class EmptyListCarry:
    """Sentinel for an empty Python list entering a traced region before
    its element type is known; the first append materializes the typed
    BoundedTensorArray (the aval-probe fixpoint in convert_while_loop
    discovers the type, exactly like None-initialized carries)."""


jax.tree_util.register_pytree_node(
    BoundedTensorArray,
    lambda ta: ((ta.buffer, ta.size, ta.ovf), None),
    lambda _, leaves: BoundedTensorArray(*leaves))
jax.tree_util.register_pytree_node(
    EmptyListCarry, lambda s: ((), None), lambda _, leaves: EmptyListCarry())
