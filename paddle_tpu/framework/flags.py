"""Global flag registry.

Reference parity: paddle/fluid/platform/flags.cc (27 DEFINE_* gflags),
pybind/global_value_getter_setter.cc:325 (REGISTER_PUBLIC_GLOBAL_VAR) and the
Python bridge paddle.set_flags/get_flags (python/paddle/fluid/framework.py:5743).

TPU-first: one Python-side registry; every flag can be seeded from the
environment (``FLAGS_xxx=...``) at import, exactly like InitGflags
(platform/init.h:34) parses env on startup. Subsystems read flags lazily so
set_flags takes effect between steps.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

_REGISTRY: Dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "value", "default", "doc", "validator", "writable")

    def __init__(self, name, default, doc="", validator=None, writable=True):
        self.name = name
        self.default = default
        self.doc = doc
        self.validator = validator
        self.writable = writable
        self.value = self._from_env(default)

    def _from_env(self, default):
        raw = os.environ.get("FLAGS_" + self.name)
        if raw is None:
            return default
        if isinstance(default, bool):
            return raw.lower() in ("1", "true", "yes", "on")
        if isinstance(default, int):
            return int(raw)
        if isinstance(default, float):
            return float(raw)
        return raw


def define_flag(name: str, default: Any, doc: str = "",
                validator: Optional[Callable[[Any], bool]] = None,
                writable: bool = True) -> None:
    if name in _REGISTRY:
        # Re-registration with the SAME default is an idempotent no-op
        # (module reload); a DIFFERENT default used to silently overwrite
        # nothing -- the second caller believed its default won when the
        # first registration's value stayed live.  Make the conflict loud.
        prev = _REGISTRY[name]
        if prev.default != default or type(prev.default) is not type(default):
            raise ValueError(
                f"flag {name!r} is already registered with default "
                f"{prev.default!r}; re-registration with a different "
                f"default {default!r} would be silently ignored -- "
                f"rename the flag or reuse the existing registration")
        return
    _REGISTRY[name] = _Flag(name, default, doc, validator, writable)


def flags_snapshot() -> Dict[str, Any]:
    """Snapshot every flag's CURRENT value -> {name: value}.  Pair with
    :func:`flags_restore` so tests mutate flags without hand-rolled
    try/finally bookkeeping::

        snap = flags_snapshot()
        try:
            set_flags({"FLAGS_graph_lint": "error"})
            ...
        finally:
            flags_restore(snap)
    """
    return {name: f.value for name, f in _REGISTRY.items()}


def flags_restore(snapshot: Dict[str, Any]) -> None:
    """Restore values captured by :func:`flags_snapshot`.  Bypasses the
    writable/validator gates (the values were live before, so they are
    valid by construction); flags registered after the snapshot keep
    their current value."""
    for name, value in snapshot.items():
        f = _REGISTRY.get(name)
        if f is not None:
            f.value = value


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags parity (framework.py:5743)."""
    for name, value in flags.items():
        key = name[6:] if name.startswith("FLAGS_") else name
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {name!r}")
        flag = _REGISTRY[key]
        if not flag.writable:
            raise ValueError(f"flag {name!r} is not public-writable")
        if flag.validator is not None and not flag.validator(value):
            raise ValueError(f"invalid value {value!r} for flag {name!r}")
        flag.value = value


def get_flags(flags) -> Dict[str, Any]:
    """paddle.get_flags parity (framework.py:5766)."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        key = name[6:] if name.startswith("FLAGS_") else name
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {name!r}")
        out[name] = _REGISTRY[key].value
    return out


def flag(name: str) -> Any:
    return _REGISTRY[name].value


def all_flags() -> Dict[str, Any]:
    return {f"FLAGS_{k}": v.value for k, v in _REGISTRY.items()}


# ---- Core flags (subset of platform/flags.cc relevant on TPU) ----------------
define_flag("check_nan_inf", False,
            "Sweep op outputs for NaN/Inf each eager op (flags.cc:45 parity; "
            "TPU impl uses jnp.isfinite reductions).")
define_flag("benchmark", False,
            "Synchronize after every eager op and record timings "
            "(operator.cc:1163 parity; TPU impl: block_until_ready per op).")
define_flag("eager_delete_tensor_gb", 0.0,
            "GC threshold parity (flags.cc); no-op on TPU (XLA owns buffers).")
define_flag("use_pallas_kernels", True,
            "Lower hot fused ops (attention, layernorm) through Pallas TPU "
            "kernels when running on TPU; fall back to jnp otherwise.")
define_flag("use_pallas_fused_bn", False,
            "Route channels-last train-mode batch_norm through the Pallas "
            "fused-BN kernels (ops/pallas/fused_bn.py). OFF by default: "
            "measured SLOWER end-to-end than XLA's own epilogue fusion on "
            "the v5e bench chip (974 vs 1971 img/s ResNet-50) -- see "
            "PERF.md's round-4 roofline correction.")
define_flag("use_pallas_fused_conv", False,
            "Route eligible NHWC train-mode conv+BN(+ReLU) chains (and the "
            "space-to-depth ResNet stem) through the fused Pallas conv "
            "pipeline (ops/pallas/fused_conv.py). OFF by default under the "
            "measured-crossover honesty rule: the default flips only with "
            "an end-to-end ResNet-50 win recorded on the bench chip in "
            "PERF.md round-6 (the BN-only predecessor measured 974 vs 1971 "
            "img/s because opaque customs break XLA's conv fusion; this "
            "kernel owns the whole chain precisely to beat that). Legacy "
            "env PADDLE_TPU_PALLAS_CONV=1 also honored.")
define_flag("allocator_strategy", "auto_growth",
            "allocator_strategy parity (allocator_strategy.h:21); informational "
            "on TPU -- PJRT owns HBM via BFC.")
define_flag("cudnn_deterministic", False,
            "Determinism flag parity (flags.cc:98); on TPU compiled programs "
            "are deterministic by default.")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "Memory-fraction parity; forwarded informationally.")
define_flag("paddle_num_threads", 1, "Host-side intra-op threads parity.")
define_flag("static_executor_mode", "fused",
            "'fused' compiles a whole Program into one XLA computation "
            "(idiomatic TPU); 'op_by_op' interprets per-op for debugging "
            "(executor.cc:473 hot-loop parity).")
define_flag("enable_profiler",
            os.environ.get("PADDLE_TPU_PROFILE", "").lower()
            in ("1", "true", "yes", "on"),
            "Emit host-side RecordEvent spans from the instrumented "
            "runtime paths (static executor, @to_static dispatch, "
            "TrainStep, device.synchronize) even outside an active "
            "profiler.Profiler record window. Seeded by FLAGS_enable_"
            "profiler or PADDLE_TPU_PROFILE; a Profiler's record phase "
            "turns the spans on regardless of this flag.")
define_flag("train_sentinel",
            os.environ.get("PADDLE_TPU_SENTINEL", "").lower()
            in ("1", "true", "yes", "on"),
            "In-graph numerics sentinel: one fused isfinite reduction over "
            "loss + gradients inside the jitted train step; a non-finite "
            "step is skipped in-graph (params/opt state keep their old "
            "values) and counted in the train_skipped_steps gauge. "
            "Off-path cost when disabled: one Python branch at trace "
            "time, zero graph change. Seeded by PADDLE_TPU_SENTINEL.")
define_flag("sentinel_max_bad_steps", 8,
            "Abort bound for the numerics sentinel: this many CONSECUTIVE "
            "skipped (non-finite) steps raises FloatingPointError with a "
            "diagnostic dump (offending tensor, step, last-good "
            "checkpoint) instead of silently burning the job.",
            validator=lambda v: int(v) >= 1)
define_flag("ckpt_keep", 3,
            "Checkpoint retention: the CheckpointManager keeps this many "
            "newest COMPLETE step checkpoints and GCs the rest (plus "
            "crashed-save debris older than the newest complete step). "
            "0 keeps everything.",
            validator=lambda v: int(v) >= 0)
define_flag("store_max_retries", 3,
            "TCPStore client ops (set/get/add/wait) retry transient "
            "socket errors (ECONNRESET, timeouts) this many times with "
            "exponential backoff + jitter, reconnecting between attempts "
            "— a bounced rendezvous server no longer kills workers.",
            validator=lambda v: int(v) >= 0)
define_flag("store_retry_backoff", 0.05,
            "Base delay (seconds) of the TCPStore retry backoff; attempt "
            "k sleeps base * 2^k plus up to 50% deterministic jitter.",
            validator=lambda v: float(v) > 0)
define_flag("use_int8_inference",
            os.environ.get("PADDLE_TPU_INT8", "").lower()
            in ("1", "true", "yes", "on"),
            "Serve frozen int8 inference programs: the Predictor prefers a "
            "model prefix's '.int8' sibling artifact (quantization/"
            "freeze.py save_int8_model) and keys its AOT executable cache "
            "on the quant signature so int8 and float executables never "
            "collide. Off-path cost: one Python branch at predictor "
            "construction. Seeded by PADDLE_TPU_INT8.")
define_flag("wide_deep_device_dedup",
            os.environ.get("PADDLE_TPU_WD_DEDUP", "").lower()
            in ("1", "true", "yes", "on"),
            "Wide&Deep cached-mode id dedup runs ON DEVICE (static-shape "
            "sort-based unique + segment-ids, rec/wide_deep.py) instead of "
            "host np.unique over the full B*S id block; the host resolves "
            "only the deduped prefix against the hot-row cache. OFF by "
            "default pending a chip measurement (PERF.md int8/dedup "
            "round); the hot-row cache and capacity behavior are "
            "unchanged. Seeded by PADDLE_TPU_WD_DEDUP.")
define_flag("jit_ledger_dir",
            os.environ.get("PADDLE_TPU_JIT_LEDGER_DIR", ""),
            "When non-empty, recompile-ledger events (profiler.ledger) "
            "additionally stream as JSONL via utils.monitor.LogWriter "
            "into this directory. The in-memory event ring and the "
            "jit_compile_count/jit_cache_hit/jit_compile_ms_total stats "
            "are always maintained.")
define_flag("graph_lint",
            os.environ.get("PADDLE_TPU_GRAPH_LINT", "off").lower()
            or "off",
            "Graph-lint tri-state (paddle_tpu.analysis): 'off' = no "
            "analysis (one Python branch per compile, zero per step); "
            "'warn' = run the pass suite over every fresh jit/Executor/"
            "TrainStep trace and emit GraphLintWarning + gauges/JSONL; "
            "'error' = additionally raise EnforceError at trace time on "
            "ERROR-severity findings (host-transfer, donation, "
            "collective-consistency). Seeded by PADDLE_TPU_GRAPH_LINT.",
            validator=lambda v: str(v).lower() in ("off", "warn", "error"))
define_flag("graph_lint_suppress", "",
            "Comma-separated lint pass ids to skip (e.g. "
            "'layout,dead-fetch'); the scoped analysis.suppress() context "
            "manager composes with this.")
define_flag("hlo_audit",
            os.environ.get("PADDLE_TPU_HLO_AUDIT", "off").lower()
            or "off",
            "Compiled-program audit tri-state (paddle_tpu.analysis.hlo): "
            "'off' = no audit (one Python branch per fresh TrainStep "
            "compile, zero per step); 'warn' = AOT-relower every fresh "
            "train-step signature, inspect the partitioned HLO "
            "(collective census, ZeRO layout contract, per-device "
            "memory) and emit HloAuditWarning + hlo_audit_* gauges/"
            "JSONL; 'error' = additionally raise EnforceError BEFORE "
            "the step executes when an ERROR-severity finding fires "
            "(hlo-full-gather: de-sharded ZeRO state). NB: warn/error "
            "add one extra XLA compile per fresh signature (the audit "
            "lowers its own executable). Seeded by PADDLE_TPU_HLO_AUDIT.",
            validator=lambda v: str(v).lower() in ("off", "warn", "error"))
define_flag("hlo_audit_dir",
            os.environ.get("PADDLE_TPU_HLO_AUDIT_DIR", ""),
            "When non-empty, every HLO-audit diagnostic additionally "
            "streams as JSONL via utils.monitor.LogWriter into this "
            "directory (next to the recompile ledger's "
            "PADDLE_TPU_JIT_LEDGER_DIR sink). Gauges are always "
            "maintained.")
define_flag("hlo_audit_hbm_gb", 16.0,
            "Per-device HBM budget (GiB) for the hlo-memory-budget audit "
            "pass: a compiled step whose per-device args+outputs+temps+"
            "code exceed it is flagged. Default 16 GiB (v5e).",
            validator=lambda v: float(v) > 0)
define_flag("hlo_audit_collective_budget", 0.9,
            "Collective-bound threshold for the hlo-collective-budget "
            "audit pass: flagged when ring-model interconnect wire bytes "
            "exceed this fraction of the program's total bytes accessed "
            "(cost_analysis) — the step scales with the network, not the "
            "chip.",
            validator=lambda v: float(v) > 0)
define_flag("graph_lint_dir",
            os.environ.get("PADDLE_TPU_GRAPH_LINT_DIR", ""),
            "When non-empty, every lint diagnostic additionally streams "
            "as JSONL via utils.monitor.LogWriter into this directory "
            "(next to the recompile ledger's PADDLE_TPU_JIT_LEDGER_DIR "
            "sink). Gauges are always maintained.")
define_flag("autoshard",
            os.environ.get("PADDLE_TPU_AUTOSHARD", "off").lower()
            or "off",
            "Auto-sharding tri-state (paddle_tpu.analysis.autoshard): "
            "'off' = no rule matching (one Python branch per TrainStep "
            "state init, zero per step); 'propose' = compute the "
            "rules-table sharding plan for every TrainStep model and "
            "publish it (autoshard_* gauges + graph-lint JSONL sink) "
            "WITHOUT mutating annotations; 'apply' = additionally write "
            "the proposed PartitionSpecs onto unannotated parameters "
            "before the sharding tree is built (hand shard_parameter "
            "annotations always win; a contradicting rule is an "
            "autoshard-conflict lint finding, ERROR severity). Seeded "
            "by PADDLE_TPU_AUTOSHARD.",
            validator=lambda v: str(v).lower() in ("off", "propose",
                                                   "apply"))
define_flag("autoshard_rules",
            os.environ.get("PADDLE_TPU_AUTOSHARD_RULES", "default")
            or "default",
            "Which PartitionRules table drives auto-sharding (and the "
            "rule-naming in sharding-coverage diagnostics): 'default' "
            "(transformer+conv+embedding), 'transformer', 'conv', "
            "'embedding', or any name published via "
            "analysis.autoshard.register_rules_table. Resolution is "
            "lazy, so custom tables may register after import. Seeded "
            "by PADDLE_TPU_AUTOSHARD_RULES.",
            validator=lambda v: bool(str(v).strip()))

# ---- Mesh-sharded embedding tables (paddle_tpu.rec.sharded_embedding) -------
define_flag("sharded_embedding",
            os.environ.get("PADDLE_TPU_SHARDED_EMB", "").lower()
            in ("1", "true", "yes", "on"),
            "Row-partition the CTR deep-leg embedding table over a mesh "
            "axis with in-graph all-to-all lookup (rec/sharded_embedding."
            "py): deduped ids bucket by owner shard, route via "
            "lax.all_to_all inside shard_map, gather from the local table "
            "slice and route back — the HeterPS hashtable seat done "
            "TPU-style, opening tables single-chip HBM cannot hold. "
            "Consumed by WideDeepTrainer (cached mode: the hot-row device "
            "cache short-circuits the all-to-all for the skewed head; "
            "only cache misses route) and HeterTrainer (device service "
            "leg). OFF by default: the replicated/host-table path is "
            "unchanged and bit-identical (one Python branch at trainer "
            "construction). Seeded by PADDLE_TPU_SHARDED_EMB.")
define_flag("sharded_embedding_axis", "dp",
            "Mesh axis the sharded embedding tables row-partition over "
            "(P(axis, None) on the table parameter, so ZeRO/autoshard "
            "layering composes). 'dp' rides the widest axis of CTR "
            "meshes; any named axis of the live mesh is accepted.",
            validator=lambda v: str(v) in ("dp", "mp", "pp", "sp"))
define_flag("sharded_embedding_bucket_cap", 0,
            "Static per-destination bucket capacity for the all-to-all "
            "routing (ids each shard may send to one owner per step). 0 "
            "= auto: the safe cap (the shard's whole request slice — no "
            "overflow possible). A positive cap shrinks the routed "
            "buffers for flat id distributions; the trainers detect "
            "overflow (one scalar D2H, the device-dedup protocol) and "
            "re-run one octave up, so a too-small cap costs recompiles, "
            "never correctness.",
            validator=lambda v: int(v) >= 0)

# ---- Expert-parallel Mixture-of-Experts (paddle_tpu.nn.layer.moe) -----------
define_flag("moe_capacity_factor",
            float(os.environ.get("PADDLE_TPU_MOE_CAPACITY_FACTOR", "1.25")
                  or 1.25),
            "Default capacity factor of MoE token dispatch: each routing "
            "group may park at most ceil(cf * tokens * top_k / E) "
            "assignments on one expert; overflow assignments DROP (the "
            "token keeps its residual) and are counted in the "
            "moe_tokens_dropped_total metric.  1.0 = exactly-balanced "
            "budget, 1.25 = the usual head-room.  Only consulted when a "
            "MoELayer/GPTMoEConfig leaves capacity_factor unset; models "
            "without MoE layers are untouched (dense FFN is the default "
            "everywhere).  Seeded by PADDLE_TPU_MOE_CAPACITY_FACTOR.",
            validator=lambda v: float(v) > 0)
define_flag("moe_top_k", 2,
            "Default top-k of MoE softmax gating (k experts per token; "
            "k=2 renormalizes the chosen pair, k=1 is the Switch rule). "
            "Only consulted when a MoELayer/GPTMoEConfig leaves top_k "
            "unset.  Seeded by FLAGS_moe_top_k.",
            validator=lambda v: int(v) in (1, 2))
define_flag("moe_axis",
            os.environ.get("PADDLE_TPU_MOE_AXIS", "ep") or "ep",
            "Mesh axis MoE expert stacks shard over (P(axis, None, None) "
            "on the stacked expert parameters) and token rows route "
            "across: 'ep' is the dedicated expert-parallel axis "
            "(parallel.mesh.EP_AXIS); 'dp' rides the data axis (classic "
            "EP=DP).  A mesh without the axis falls back to the meshless "
            "local dispatch (single shard, no all_to_all).  The "
            "autoshard 'expert' rules table reads this flag, so rule "
            "proposals and layer annotations always name the same axis. "
            "Seeded by PADDLE_TPU_MOE_AXIS.",
            validator=lambda v: str(v) in ("ep", "dp", "mp", "pp", "sp"))

# ---- Serving engine (paddle_tpu.serving) ------------------------------------
define_flag("serving_buckets", "1,2,4,8,16,32,64",
            "Default batch-bucket ladder for the serving engine: pending "
            "requests continuously batch into the smallest bucket that "
            "holds them and pad up, so steady-state serving only ever "
            "executes shapes compiled at warm-up (zero recompiles). "
            "Per-model override via ModelSpec(buckets=...).",
            validator=lambda v: all(int(b) > 0 for b in
                                    str(v).split(",") if b.strip()))
define_flag("serving_workers", 2,
            "Serving worker threads per Server; each worker runs its own "
            "Predictor.clone() (AnalysisPredictor::Clone seat: shared "
            "weights + executables, per-clone IO buffers).",
            validator=lambda v: int(v) >= 1)
define_flag("serving_queue_capacity", 1024,
            "Bound on requests pending in the serving queue; submit() past "
            "it blocks up to its timeout then raises UnavailableError "
            "(backpressure instead of unbounded host memory).",
            validator=lambda v: int(v) >= 1)
define_flag("serving_batch_timeout_ms", 2.0,
            "How long the continuous batcher holds a non-full batch open "
            "for more arrivals before dispatching what it has. 0 "
            "dispatches immediately (lowest latency, smallest batches).",
            validator=lambda v: float(v) >= 0)
define_flag("serving_pipeline_depth", 2,
            "Batches a worker keeps in flight on device before fencing "
            "the oldest: H2D + dispatch of batch N+1 overlap execution "
            "of batch N (jit-served models; the executor path is "
            "synchronous). 1 disables pipelining.",
            validator=lambda v: int(v) >= 1)
define_flag("serving_strict", True,
            "Steady-state shape discipline: a batch whose bucket has no "
            "warm-up-compiled executable FAILS (its requests get "
            "EnforceError) instead of compiling on the fly. Disable only "
            "for debugging; any fallback compile is ledgered and counted "
            "in the serving_steady_compiles gauge either way.")
define_flag("serving_metrics_window", 2048,
            "Sliding-window size (completed requests) of the per-model "
            "serving latency reservoir behind the p50/p99 gauges.",
            validator=lambda v: int(v) >= 16)

# ---- Multi-host cluster serving (paddle_tpu.serving.cluster) ----------------
define_flag("serving_replicas",
            int(os.environ.get("PADDLE_TPU_SERVING_REPLICAS", "1")),
            "Replica count the cluster serving CLI (tools/serve.py "
            "--router) spawns behind the front-end router. 1 (the "
            "default) is the single-process path — no router, no RPC, "
            "one branch.",
            validator=lambda v: int(v) >= 1)
define_flag("serving_role",
            os.environ.get("PADDLE_TPU_SERVING_ROLE", "both").lower()
            or "both",
            "Worker-pool role of this serving process: 'both' (default; "
            "full prefill+decode grids, single-process behavior "
            "unchanged), 'prefill' (compute-bound pool: warm-up compiles "
            "ONLY the prefill grid, serves prefill_handoff), or 'decode' "
            "(memory-bound pool: ONLY the decode grid, serves "
            "decode_from_handoff). Disaggregation is these two pools "
            "plus the explicit KV-cache handoff between them.",
            validator=lambda v: str(v).lower() in ("both", "prefill",
                                                   "decode"))
define_flag("router_heartbeat_s",
            float(os.environ.get("PADDLE_TPU_ROUTER_HEARTBEAT_S", "2.0")),
            "Interval at which a cluster replica publishes liveness to "
            "the rendezvous TCPStore (the elastic HeartbeatReporter "
            "reused for serving).",
            validator=lambda v: float(v) > 0)
define_flag("router_stale_after_s",
            float(os.environ.get("PADDLE_TPU_ROUTER_STALE_AFTER_S",
                                 "10.0")),
            "Router-side eviction threshold: a replica whose heartbeat "
            "is older than this is evicted from dispatch (its in-flight "
            "requests re-dispatch to surviving replicas; nothing is "
            "lost past the submit ack).",
            validator=lambda v: float(v) > 0)
define_flag("router_retry_backoff_s",
            float(os.environ.get("PADDLE_TPU_ROUTER_RETRY_BACKOFF_S",
                                 "0.05")),
            "Default per-replica backoff after an UNAVAILABLE "
            "backpressure rejection that carried no retry-after hint "
            "(rejections normally carry the queue's own estimate).",
            validator=lambda v: float(v) >= 0)

# ---- Elastic cluster lifecycle (serving/cluster/lifecycle.py) ---------------
define_flag("autoscale_queue_high",
            float(os.environ.get("PADDLE_TPU_AUTOSCALE_QUEUE_HIGH",
                                 "8.0")),
            "Scale-up trigger: mean queue depth per live replica above "
            "which the AutoscaleController spawns another replica "
            "(subject to its max and cooldown).",
            validator=lambda v: float(v) > 0)
define_flag("autoscale_idle_polls",
            int(os.environ.get("PADDLE_TPU_AUTOSCALE_IDLE_POLLS", "3")),
            "Scale-down trigger: consecutive controller polls the "
            "cluster must look idle (empty queues, cold retry hints) "
            "before one replica is drained and retired.",
            validator=lambda v: int(v) >= 1)
define_flag("autoscale_cooldown_polls",
            int(os.environ.get("PADDLE_TPU_AUTOSCALE_COOLDOWN_POLLS",
                               "2")),
            "Polls the controller sits out after any scale action — "
            "hysteresis so a replica mid-boot is not double-spawned and "
            "a fresh retirement is not immediately reversed.",
            validator=lambda v: int(v) >= 0)
define_flag("drain_timeout_s",
            float(os.environ.get("PADDLE_TPU_DRAIN_TIMEOUT_S", "30.0")),
            "Graceful-drain budget: how long a retiring replica may "
            "take to finish queued batches and slot-loop rows before "
            "the controller escalates to eviction (the SIGKILL-style "
            "path graceful retirement exists to avoid).",
            validator=lambda v: float(v) > 0)
define_flag("serving_tenant_quota",
            int(os.environ.get("PADDLE_TPU_SERVING_TENANT_QUOTA", "0")),
            "Default per-tenant pending-request quota in the "
            "RequestQueue (admission control): a tenant at its quota "
            "gets UnavailableError with a retry_after hint while other "
            "tenants keep their queue slots. 0 (default) = unlimited — "
            "single-tenant behavior unchanged, one branch. Per-tenant "
            "overrides via RequestQueue.set_tenant_policy.",
            validator=lambda v: int(v) >= 0)

# ---- Request tracing + typed metrics plane (paddle_tpu.profiler) ------------
define_flag("trace",
            os.environ.get("PADDLE_TPU_TRACE", "off").lower() or "off",
            "Request-scoped span tracing tri-state (profiler.tracing): "
            "'off' = no spans (one Python branch per instrumentation "
            "point); 'sample' = trace every k-th request/step where k = "
            "round(1/FLAGS_trace_sample_rate); 'full' = trace every "
            "request and training step.  Spans cover the whole serving "
            "path (submit -> queue wait -> pack -> H2D -> execute -> D2H "
            "-> reply), the train-step phase breakdown, and generate()'s "
            "prefill/decode scan boundary; recompile-ledger events "
            "auto-attach to the active span.  Host-side timing only: "
            "tracing never changes a traced program or adds a compile "
            "key.  Seeded by PADDLE_TPU_TRACE.",
            validator=lambda v: str(v).lower() in ("off", "sample",
                                                   "full"))
define_flag("trace_sample_rate", 0.01,
            "Fraction of requests/steps traced under FLAGS_trace=sample "
            "(deterministic stride sampling: every round(1/rate)-th root "
            "span is kept, so long runs converge to the rate without a "
            "per-request RNG draw).",
            validator=lambda v: 0.0 < float(v) <= 1.0)
define_flag("trace_dir",
            os.environ.get("PADDLE_TPU_TRACE_DIR", ""),
            "When non-empty, every finished span additionally streams as "
            "JSONL via utils.monitor.LogWriter into this directory "
            "(tools/obs_report.py joins these with metrics snapshots "
            "into per-request waterfalls).  The bounded in-memory span "
            "ring is always maintained while tracing is on.")
define_flag("flight_dir",
            os.environ.get("PADDLE_TPU_FLIGHT_DIR", ""),
            "When non-empty, arm the per-process flight recorder "
            "(profiler.flight): a bounded in-memory ring of recent "
            "spans, recompile-ledger events and metric snapshots, "
            "atomically persisted into this directory as "
            "postmortem_<id>.json — rewritten every "
            "FLAGS_flight_interval_s and on SIGTERM/fatal paths — so "
            "even a SIGKILLed replica leaves evidence "
            "(tools/obs_report.py --postmortem reads it).  Empty = "
            "recorder fully off (zero hot-path cost).  Seeded by "
            "PADDLE_TPU_FLIGHT_DIR.")
define_flag("flight_interval_s", 1.0,
            "Flight-recorder persistence cadence: the background dumper "
            "rewrites the postmortem artifact (atomic replace, "
            "checkpoint discipline) this often, bounding how much "
            "history an uncatchable SIGKILL can destroy.",
            validator=lambda v: float(v) > 0)
define_flag("flight_spans", 256,
            "How many most-recent finished spans (and ledger events, "
            "capped at half this) a flight-recorder dump carries — the "
            "artifact stays a bounded postmortem, not a trace archive.",
            validator=lambda v: int(v) > 0)
define_flag("log_writer_max_mb", 64.0,
            "Size cap (MiB) per LogWriter JSONL sink file (recompile "
            "ledger, graph-lint, hlo-audit, trace dirs): past the cap "
            "the file rotates ('f.jsonl' -> 'f.jsonl.1' -> 'f.jsonl.2', "
            "two rollovers kept), so a long-running serve process "
            "cannot grow any sink without bound.  0 disables rotation.",
            validator=lambda v: float(v) >= 0)

# ---- Autoregressive decoding (text.generation + serving decode) -------------
define_flag("use_flash_decode",
            os.environ.get("PADDLE_TPU_FLASH_DECODE", "").lower()
            in ("1", "true", "yes", "on"),
            "Route single-query cached attention (the decode step of "
            "generate()) through the Pallas flash-decoding kernel "
            "(ops/pallas/flash_decode.py): split-K over the cached "
            "context with an online-softmax merge, so one query row "
            "still fills the chip. OFF by default under the "
            "measured-crossover honesty rule — no chip measurement this "
            "round (PERF.md decode section records the pending state); "
            "the XLA masked-attention reference path is bit-matched by "
            "the interpret-mode tests. Seeded by PADDLE_TPU_FLASH_DECODE.")
define_flag("decode_buckets", "16,32,64,128,256,512,1024",
            "Sequence-length bucket ladder for incremental decoding: "
            "prompt lengths pad (left) up to the smallest bucket, and "
            "KV-cache lengths round up to the smallest bucket holding "
            "prompt + max_new_tokens, so generate() and the serving "
            "decode path only ever compile (batch, prefill-bucket, "
            "cache-bucket) shapes fixed at warm-up.",
            validator=lambda v: all(int(b) > 0 for b in
                                    str(v).split(",") if b.strip()))
define_flag("decode_max_len", 1024,
            "Hard ceiling on KV-cache length (prompt + generated tokens) "
            "for generate() and serving decode; requests past it raise "
            "OutOfRange instead of growing an unbounded cache shape.",
            validator=lambda v: int(v) >= 1)
define_flag("decode_slots",
            int(os.environ.get("PADDLE_TPU_DECODE_SLOTS", "0") or 0),
            "Slot count S of the iteration-level continuous-batching "
            "decode loop (serving/slots.py): ONE single-token step "
            "executable per (S, cache-bucket) in which requests occupy "
            "slots, finished rows retire at token boundaries and queued "
            "requests join by restarting a row's validity window — no "
            "recompile, no cache copy.  0 (default) keeps the "
            "run-to-completion scanned decode path byte-identical to "
            "before (one Python branch at decode-runtime load).  Seeded "
            "by PADDLE_TPU_DECODE_SLOTS.",
            validator=lambda v: 0 <= int(v) <= 256)
define_flag("prefill_chunk",
            int(os.environ.get("PADDLE_TPU_PREFILL_CHUNK", "16") or 16),
            "Chunk width T of Sarathi-style chunked prefill under the "
            "slot decode loop (FLAGS_decode_slots > 0): a joining "
            "request's prompt is split into ceil(len/T) LEFT-padded "
            "chunks interleaved with decode steps — T decode steps, one "
            "chunk, repeat — so TTFT p99 of short requests is not "
            "hostage to head-of-line long prompts.  Irrelevant when "
            "FLAGS_decode_slots == 0.  Seeded by "
            "PADDLE_TPU_PREFILL_CHUNK.",
            validator=lambda v: 1 <= int(v) <= 4096)
define_flag("prefix_cache", False,
            "Radix-trie prefix KV cache under the slot decode loop "
            "(serving/prefix_cache.py): completed prefills publish their "
            "prompt's ring-cache plane blocks back into a token-prefix "
            "trie, and a joining request restores the longest cached "
            "prefix into its validity window, chunk-prefilling only the "
            "uncached suffix.  Off (default) = the slot loop admits "
            "exactly as before (one Python branch at admission).  "
            "Requires FLAGS_decode_slots > 0 to have any effect.")
define_flag("prefix_cache_hbm_mb", 256.0,
            "Device-memory budget (MiB) of the prefix KV cache; "
            "least-recently-used unpinned leaf blocks evict until the "
            "cache fits.  0 = unbounded (the trie grows until cleared).",
            validator=lambda v: float(v) >= 0.0)
define_flag("session_store", False,
            "Parked-session KV store (serving/sessions.py): a decode "
            "request carrying a session id parks its ring-cache row as a "
            "host-RAM snapshot at turn end, and the follow-up turn "
            "restores the snapshot into a slot and decodes from the "
            "committed position instead of re-prefilling the whole "
            "history.  Graceful drain parks in-flight session rows for "
            "migration instead of waiting them out.  Off (default) = "
            "session ids are ignored; off-path is one Python branch.")
define_flag("session_store_dir", "",
            "Optional disk-spill directory for parked sessions (empty = "
            "host RAM only).  Snapshots write under the sha256-verified "
            "atomic-manifest discipline; a directory shared between "
            "replicas doubles as the migration transport — any replica "
            "can restore a session a dead replica parked there.")
define_flag("session_park_after_ms", 0,
            "Age (ms) a RAM-parked session must reach before it spills "
            "to FLAGS_session_store_dir.  0 (default) writes through to "
            "disk at park time — the mode that survives SIGKILL, since "
            "a lazily-spilled snapshot still in RAM dies with the "
            "process.  Ignored when the spill directory is unset.",
            validator=lambda v: int(v) >= 0)

# ---- Persistent executable cache (paddle_tpu.jit.persistent_cache) ----------
define_flag("executable_cache",
            os.environ.get("PADDLE_TPU_EXEC_CACHE", "off").lower()
            or "off",
            "Persistent on-disk AOT executable cache tri-state "
            "(jit/persistent_cache.py): 'off' = every fresh compile "
            "pays XLA (one Python branch per fresh-compile path, zero "
            "per step); 'read' = fresh compiles first probe "
            "FLAGS_executable_cache_dir for a serialized executable "
            "with a matching (ledger key, program identity, "
            "jaxlib/device fingerprint, lowering flags) digest and a "
            "verified sha256 — hits deserialize in O(load) and are "
            "ledgered as kind 'cache_load'; 'readwrite' additionally "
            "serializes every fresh compile back into the dir (one "
            "host compiles, N hosts load).  Wired into @to_static "
            "dispatch, the static Executor, TrainStep.aot_compile "
            "(and so HLO-audit lowerings), and the serving warm-up "
            "grids (dense + decode + speculative).  Seeded by "
            "PADDLE_TPU_EXEC_CACHE.",
            validator=lambda v: str(v).lower() in ("off", "read",
                                                   "readwrite"))
define_flag("executable_cache_dir",
            os.environ.get("PADDLE_TPU_EXEC_CACHE_DIR", ""),
            "Directory of the persistent executable cache (entries: "
            "<digest>.pjrt payload + <digest>.json sha256 manifest, "
            "written with the checkpoint subsystem's atomic "
            "temp+fsync+rename discipline).  Empty disables the cache "
            "regardless of FLAGS_executable_cache — both must be set "
            "(tools/serve.py --cache-dir sets both).  Seeded by "
            "PADDLE_TPU_EXEC_CACHE_DIR.")
define_flag("executable_cache_max_gb",
            float(os.environ.get("PADDLE_TPU_EXEC_CACHE_MAX_GB", "0")
                  or 0),
            "Payload-size cap (GiB) for the persistent executable "
            "cache: after each store, least-recently-used entries are "
            "evicted until the cache fits.  0 = unbounded (GC via "
            "tools/exec_cache.py gc --max-gb/--max-age).  Seeded by "
            "PADDLE_TPU_EXEC_CACHE_MAX_GB.",
            validator=lambda v: float(v) >= 0)

# ---- Speculative decoding + quantized KV cache (text.speculative) -----------
define_flag("spec_decode",
            os.environ.get("PADDLE_TPU_SPEC_DECODE", "").lower()
            in ("1", "true", "yes", "on"),
            "Serve decode models through draft/target speculative "
            "decoding (text/speculative.py) when the DecodeModelSpec "
            "carries a draft layer: a small GPT drafts FLAGS_spec_gamma "
            "tokens per step, the target verifies all of them in ONE "
            "batched forward, and greedy acceptance walks the longest "
            "agreeing prefix — output tokens are bit-identical to plain "
            "greedy decode of the target (acceptance/rollback is "
            "lossless by construction), at up to gamma+1 tokens per "
            "target pass.  OFF by default: the plain Generator path is "
            "unchanged (one Python branch at decode-runtime load).  An "
            "explicit generate(draft_model=...) call opts in regardless "
            "of the flag.  Seeded by PADDLE_TPU_SPEC_DECODE.")
define_flag("spec_gamma", 4,
            "Tokens the draft model proposes per speculative step "
            "(gamma).  Each step costs gamma+1 draft forwards plus ONE "
            "gamma+1-wide target verify forward and commits 1..gamma+1 "
            "tokens; higher gamma pays off when draft/target agreement "
            "is high.  Per-call override via "
            "SpeculativeGenerator(gamma=...).",
            validator=lambda v: 1 <= int(v) <= 16)
define_flag("kv_cache_dtype",
            os.environ.get("PADDLE_TPU_KV_CACHE_DTYPE", "bf16").lower()
            or "bf16",
            "Storage dtype of the decode KV ring cache: 'bf16' (native "
            "model dtype planes — today's layout) or 'int8' (int8 rows "
            "+ per-(token, head) f32 scales as extra cache planes "
            "written at the same traced cache_position), halving "
            "cached-context HBM.  The dequant is fused into the "
            "flash-decode kernel's split-K loop when "
            "FLAGS_use_flash_decode dispatches, and falls back to a "
            "dequantize-then-attend XLA read otherwise.  One Python "
            "branch at cache init; flipping it recompiles the generate "
            "executables (the cache dtype is part of the compile key). "
            "Seeded by PADDLE_TPU_KV_CACHE_DTYPE.",
            validator=lambda v: str(v).lower() in ("bf16", "int8"))
