"""Rich error layer: PADDLE_ENFORCE parity.

Reference parity: paddle/fluid/platform/enforce.h (PADDLE_ENFORCE_* macro
family + EnforceNotMet) and paddle/fluid/platform/errors.h (the error-code
taxonomy: InvalidArgument, NotFound, OutOfRange, AlreadyExists,
ResourceExhausted, PreconditionNotMet, PermissionDenied, ExecutionTimeout,
Unimplemented, Unavailable, Fatal, External).

TPU-shape: the reference's macros capture __FILE__/__LINE__ and build a
C++ stack summary; here each error type is an exception class carrying the
error-code name, and ``op_context`` wraps op dispatch so any failure inside
a primitive (shape mismatch, XLA compile error) resurfaces with the
operator name and argument summary attached — the OperatorWithKernel
try/catch at operator.cc:1093.
"""
from __future__ import annotations

import contextlib
import functools


class EnforceNotMet(RuntimeError):
    """Base enforce failure (enforce.h EnforceNotMet)."""

    code = "LEGACY"

    def __init__(self, msg, op=None):
        self.op = op
        if op:
            msg = f"(op: {op}) {msg}"
        super().__init__(f"[{self.code}] {msg}")


class InvalidArgumentError(EnforceNotMet):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceNotMet):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    """Transient refusal (backpressure, closed queue).  A rejection that
    expects the caller to come back carries a machine-readable
    ``retry_after_s`` hint so a router can back off the one saturated
    replica instead of treating the rejection as a death and evicting
    it; ``None`` means "no estimate" (e.g. the resource is gone)."""

    code = "UNAVAILABLE"

    def __init__(self, msg, op=None, retry_after_s=None):
        super().__init__(msg, op)
        self.retry_after_s = retry_after_s


class FatalError(EnforceNotMet):
    code = "FATAL"


class ExternalError(EnforceNotMet):
    code = "EXTERNAL"


# -- enforce checks (PADDLE_ENFORCE_* macros) ---------------------------------

def enforce(cond, msg="enforce failed", exc=InvalidArgumentError, op=None):
    """PADDLE_ENFORCE(cond, ...)."""
    if not cond:
        raise exc(msg, op=op)


def enforce_not_none(value, name="value", op=None):
    """PADDLE_ENFORCE_NOT_NULL."""
    if value is None:
        raise NotFoundError(f"{name} should not be None", op=op)
    return value


def enforce_eq(a, b, msg=None, op=None):
    """PADDLE_ENFORCE_EQ."""
    if a != b:
        raise InvalidArgumentError(
            msg or f"expected {a!r} == {b!r}", op=op)


def enforce_ne(a, b, msg=None, op=None):
    if a == b:
        raise InvalidArgumentError(
            msg or f"expected {a!r} != {b!r}", op=op)


def enforce_gt(a, b, msg=None, op=None):
    if not a > b:
        raise InvalidArgumentError(msg or f"expected {a!r} > {b!r}", op=op)


def enforce_ge(a, b, msg=None, op=None):
    if not a >= b:
        raise InvalidArgumentError(msg or f"expected {a!r} >= {b!r}", op=op)


def enforce_lt(a, b, msg=None, op=None):
    if not a < b:
        raise InvalidArgumentError(msg or f"expected {a!r} < {b!r}", op=op)


def enforce_le(a, b, msg=None, op=None):
    if not a <= b:
        raise InvalidArgumentError(msg or f"expected {a!r} <= {b!r}", op=op)


def enforce_shape_match(got, expected, name="tensor", op=None):
    """Shape check with a reference-style actionable message."""
    if tuple(got) != tuple(expected):
        raise InvalidArgumentError(
            f"{name} shape mismatch: got {list(got)}, expected "
            f"{list(expected)}", op=op)


# -- op dispatch wrapping ------------------------------------------------------

def _summarize(args):
    parts = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None:
            parts.append(f"{dtype}[{','.join(map(str, shape))}]")
        else:
            parts.append(repr(a)[:40])
    return ", ".join(parts)


# error types that carry their own precise diagnostics and must escape
# op_context unwrapped (e.g. dy2static's guided conversion errors)
_PASSTHROUGH = []


def register_passthrough(cls):
    """Exempt an error class from op-context wrapping."""
    if cls not in _PASSTHROUGH:
        _PASSTHROUGH.append(cls)
    return cls


@contextlib.contextmanager
def op_context(op_name, args=()):
    """Attach operator context to any error escaping an op's kernel —
    the OperatorWithKernel::RunImpl try/catch (operator.cc:1093) that turns
    a bare kernel failure into an EnforceNotMet with op provenance."""
    try:
        yield
    except EnforceNotMet:
        raise
    except tuple(_PASSTHROUGH):
        raise
    except (TypeError, ValueError, IndexError, ZeroDivisionError) as e:
        raise InvalidArgumentError(
            f"{e} [operands: {_summarize(args)}]", op=op_name) from e
    except NotImplementedError as e:
        raise UnimplementedError(str(e), op=op_name) from e
    except RuntimeError as e:
        raise ExternalError(
            f"{e} [operands: {_summarize(args)}]", op=op_name) from e
