"""Seeded RNG generator.

Reference parity: paddle/fluid/framework/generator.h:39-62 (per-device seeded
mt19937 Generator) and paddle.seed. TPU-first: the generator owns a JAX PRNG
key and hands out split subkeys. Under a jit trace (to_static / Executor
compile) random ops must NOT burn host entropy per call -- the tracer pushes a
*traced* key onto the stack so randomness is functionalized into the compiled
program (fresh per step via a counter input).
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """Global RNG: eager ops draw fresh subkeys; manual_seed restores determinism."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = seed
        self._count = 0
        # stack of traced keys pushed by jit tracers (innermost wins)
        self._traced: list = []

    def manual_seed(self, seed: int):
        with self._lock:
            self._seed = int(seed)
            self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """A fresh PRNG key. Inside a trace, fold a counter into the traced key."""
        if self._traced:
            base, holder = self._traced[-1]
            holder[0] += 1
            return jax.random.fold_in(base, holder[0])
        with self._lock:
            self._count += 1
            c = self._count
        return jax.random.fold_in(jax.random.key(self._seed), c)

    def push_traced_key(self, key):
        self._traced.append((key, [0]))

    def pop_traced_key(self):
        self._traced.pop()

    def state(self):
        return {"seed": self._seed, "count": self._count}

    def set_state(self, state):
        self._seed = state["seed"]
        self._count = state["count"]


default_generator = Generator(seed=np.random.SeedSequence().entropy % (2 ** 31))


def seed(value: int) -> Generator:
    """paddle.seed parity (python/paddle/framework/random.py)."""
    return default_generator.manual_seed(value)


def get_rng_state():
    return default_generator.state()


def set_rng_state(state):
    default_generator.set_state(state)


# -- static-program randomness ---------------------------------------------
# A key recorded into a Program would otherwise be a baked CONSTANT (same
# dropout mask / same negatives on every Executor.run and on every step of
# a train_from_dataset scan).  static_advancing_key records a SELF-
# ADVANCING key instead: a persistable holds raw int32 key data, and a
# recorded key_advance op folds it forward and writes back to the SAME
# var name — the executor carries it as a written persistable, so the key
# advances per run AND per scanned step.

def ensure_key(k):
    """Typed PRNG key passthrough; raw int32 key data (the static-program
    carrier — Variables cannot hold typed key avals) is rewrapped."""
    import jax
    import jax.numpy as jnp
    if jnp.issubdtype(k.dtype, jax.dtypes.prng_key):
        return k
    return jax.random.wrap_key_data(
        jax.lax.bitcast_convert_type(k, jnp.uint32))


def key_raw(key):
    """Typed PRNG key -> raw int32 data (Variable-representable)."""
    import jax
    import jax.numpy as jnp
    return jax.lax.bitcast_convert_type(jax.random.key_data(key), jnp.int32)


def _advance_key_fn(raw):
    import jax
    return key_raw(jax.random.fold_in(ensure_key(raw), 1))


_advance_p = None


def register_key_advance():
    """Create the key_advance primitive (idempotent).  Called at package
    import so DESERIALIZED programs containing the op resolve it in a
    fresh process, not only after static_advancing_key ran there."""
    global _advance_p
    if _advance_p is None:
        from .primitive import Primitive
        _advance_p = Primitive("key_advance", _advance_key_fn)
    return _advance_p


def static_advancing_key(tag: str = "rng"):
    """Record a self-advancing key into the current Program; returns the
    key Variable (raw int32 data — consumers rewrap via ensure_key)."""
    from ..static.program import current_block
    from ..static.executor import global_scope
    advance = register_key_advance()
    block = current_block()
    name = f"@{tag}_key_{len(block.ops)}"
    raw0 = key_raw(default_generator.next_key())
    var = block.create_var(name=name, shape=list(raw0.shape),
                           dtype="int32", persistable=True)
    global_scope().set_var(name, raw0)
    # fresh scopes / deserialized programs are seeded by the Executor
    # (_collect_persistables treats key_advance inputs as self-seeding)
    out = advance(var)
    # self-aliasing write: the op's output takes the persistable's name,
    # making it a WRITTEN persistable (scan-carried, scope-written-back);
    # drop the auto-declared output var so no orphan metadata rides along
    auto_name = out.name
    out.op.output_names[0] = name
    block.vars.pop(auto_name, None)
    return block.var(name)
