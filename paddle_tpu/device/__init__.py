"""paddle.device parity: device query/selection over PJRT.

Reference parity: paddle/fluid/platform/init.cc InitDevices + Python
paddle.device package. Device discovery is PJRT's; these are thin queries.
"""
from __future__ import annotations

import jax

from ..framework.place import (  # noqa: F401
    CPUPlace, TPUPlace, CUDAPlace, set_device, get_device, current_place,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def device_count():
    return len(jax.devices())


def is_compiled_with_cinn():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def synchronize(device=None):
    """cudaDeviceSynchronize parity: drain pending async work. Note: on a
    remote-tunneled TPU a D2H fetch is the only true fence.  The fence is
    a profiler span (``device::synchronize``) — the Profiler uses it to
    close record windows, and its duration is the step's outstanding
    device time."""
    import jax.numpy as jnp
    from ..profiler import span as _span
    with _span("device::synchronize"):
        jnp.zeros(()).block_until_ready()


class cuda:
    """paddle.device.cuda namespace stub (queries return TPU equivalents)."""

    @staticmethod
    def device_count():
        return len([d for d in jax.devices() if d.platform != "cpu"])

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass
