"""paddle.nn.functional parity surface."""
from .activation import *  # noqa: F401,F403
from .common import (  # noqa: F401
    linear, dropout, dropout2d, dropout3d, alpha_dropout, embedding, one_hot,
    pad, interpolate, upsample, pixel_shuffle, unfold, cosine_similarity,
    bilinear, label_smooth, sequence_mask, class_center_sample,
)
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose, conv_bn_act, conv_bn_fusable,
)
from .pooling import (  # noqa: F401
    avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d, max_pool2d, max_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
    max_unpool1d, max_unpool2d, max_unpool3d,
)
from .norm import (  # noqa: F401
    batch_norm, layer_norm, instance_norm, group_norm, local_response_norm,
    normalize,
)
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    binary_cross_entropy, binary_cross_entropy_with_logits,
    sigmoid_cross_entropy_with_logits, kl_div, smooth_l1_loss, huber_loss,
    log_loss, margin_ranking_loss, hinge_loss, sigmoid_focal_loss,
    cosine_embedding_loss, ctc_loss, square_error_cost, triplet_margin_loss,
    dice_loss, npair_loss, hsigmoid_loss, rank_loss, margin_rank_loss,
    bpr_loss, center_loss, modified_huber_loss,
    teacher_student_sigmoid_loss,
)
from .attention import scaled_dot_product_attention  # noqa: F401
# re-exports the 2.x functional namespace also carries (the kernels live
# in ops/)
from ...ops.vision import (  # noqa: F401
    grid_sample, affine_grid, temporal_shift,
)
from ...ops.math_ext import diag_embed  # noqa: F401
from ...ops.math import assign  # noqa: F401
from ...ops.decode import gather_tree  # noqa: F401
