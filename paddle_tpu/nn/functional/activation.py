"""Activation functionals.

Reference parity: paddle/fluid/operators/activation_op.cc (relu, gelu, ...)
and python/paddle/nn/functional/activation.py. All are single fused XLA
expressions (VPU-friendly; XLA fuses them into surrounding matmuls).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.primitive import Primitive
from ...framework.tensor import Tensor, unwrap

_relu = Primitive("relu", jax.nn.relu)
_relu6 = Primitive("relu6", jax.nn.relu6)
_sigmoid = Primitive("sigmoid", jax.nn.sigmoid)
_tanh_p = Primitive("tanh_act", jnp.tanh)
_elu_p = Primitive("elu", lambda x, alpha=1.0: jax.nn.elu(x, alpha))
_selu_p = Primitive("selu", lambda x, scale=1.0507009873554805,
                    alpha=1.6732632423543772:
                    scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)))
_leaky = Primitive("leaky_relu", lambda x, negative_slope=0.01:
                   jax.nn.leaky_relu(x, negative_slope))
_gelu_p = Primitive("gelu", lambda x, approximate=False:
                    jax.nn.gelu(x, approximate=approximate))
_silu = Primitive("silu", jax.nn.silu)
_mish = Primitive("mish", jax.nn.mish)
_softplus_p = Primitive("softplus", lambda x, beta=1.0, threshold=20.0:
                        jnp.where(x * beta > threshold, x,
                                  jnp.log1p(jnp.exp(beta * x)) / beta))
_softsign = Primitive("softsign", jax.nn.soft_sign)
_hsig = Primitive("hard_sigmoid", lambda x, slope=1.0 / 6, offset=0.5:
                  jnp.clip(slope * x + offset, 0.0, 1.0))
_hswish = Primitive("hard_swish", lambda x:
                    x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)
_htanh = Primitive("hard_tanh", lambda x, mn=-1.0, mx=1.0: jnp.clip(x, mn, mx))
_hshrink = Primitive("hard_shrink", lambda x, threshold=0.5:
                     jnp.where(jnp.abs(x) > threshold, x, 0.0))
_sshrink = Primitive("softshrink", lambda x, threshold=0.5:
                     jnp.where(x > threshold, x - threshold,
                               jnp.where(x < -threshold, x + threshold, 0.0)))
_tshrink = Primitive("tanh_shrink", lambda x: x - jnp.tanh(x))
_thresh = Primitive("thresholded_relu", lambda x, threshold=1.0:
                    jnp.where(x > threshold, x, 0.0))
_softmax_p = Primitive("softmax", lambda x, axis=-1: jax.nn.softmax(x, axis=axis))
_log_softmax_p = Primitive("log_softmax",
                           lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis))
_logsigmoid = Primitive("logsigmoid", jax.nn.log_sigmoid)
_swish = Primitive("swish", jax.nn.silu)
_celu_p = Primitive("celu", lambda x, alpha=1.0: jax.nn.celu(x, alpha))
_prelu_p = Primitive("prelu", lambda x, w: jnp.where(x > 0, x, w * x))
_rrelu_p = Primitive("rrelu_eval", lambda x, lower=0.125, upper=1.0 / 3:
                     jnp.where(x >= 0, x, x * (lower + upper) / 2))
_glu_p = Primitive("glu", lambda x, axis=-1: (
    lambda a, b: a * jax.nn.sigmoid(b))(*jnp.split(x, 2, axis=axis)))


def relu(x, name=None):
    up = getattr(x, "_bn_act_upgrade", None)
    if up is not None:
        # conv-epilogue handshake tail (nn/layer/norm.py): rebuild the
        # conv+BN site with the ReLU fused into the Pallas apply pass; the
        # relu-less BN result this replaces is dead code under jit
        return up()
    return _relu(x)


def relu_(x):
    out = _relu(x)
    x._value, x._node, x._out_index = out._value, out._node, out._out_index
    return x


def relu6(x, name=None):
    return _relu6(x)


def sigmoid(x, name=None):
    return _sigmoid(x)


def tanh(x, name=None):
    return _tanh_p(x)


def elu(x, alpha=1.0, name=None):
    return _elu_p(x, alpha=float(alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _selu_p(x, scale=float(scale), alpha=float(alpha))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky(x, negative_slope=float(negative_slope))


def gelu(x, approximate=False, name=None):
    return _gelu_p(x, approximate=bool(approximate))


def silu(x, name=None):
    return _silu(x)


def swish(x, name=None):
    return _swish(x)


def mish(x, name=None):
    return _mish(x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _softplus_p(x, beta=float(beta), threshold=float(threshold))


def softsign(x, name=None):
    return _softsign(x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _hsig(x, slope=float(slope), offset=float(offset))


def hardswish(x, name=None):
    return _hswish(x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _htanh(x, mn=float(min), mx=float(max))


def hardshrink(x, threshold=0.5, name=None):
    return _hshrink(x, threshold=float(threshold))


def softshrink(x, threshold=0.5, name=None):
    return _sshrink(x, threshold=float(threshold))


def tanhshrink(x, name=None):
    return _tshrink(x)


def thresholded_relu(x, threshold=1.0, name=None):
    return _thresh(x, threshold=float(threshold))


def log_sigmoid(x, name=None):
    return _logsigmoid(x)


def celu(x, alpha=1.0, name=None):
    return _celu_p(x, alpha=float(alpha))


def prelu(x, weight, data_format="NCHW", name=None):
    w = weight
    if isinstance(weight, Tensor) and weight.size > 1:
        # per-channel: broadcast over channel dim
        nd = x.ndim
        shape = [1] * nd
        ch_axis = 1 if data_format == "NCHW" else nd - 1
        shape[ch_axis] = weight.size
        from ...ops import reshape
        w = reshape(weight, shape)
    return _prelu_p(x, w)


_rrelu_train = Primitive("rrelu_train", lambda v, aa: jnp.where(v >= 0, v, v * aa))


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=False, name=None):
    if training:
        from ...framework.random import default_generator
        key = default_generator.next_key()
        xv = unwrap(x)
        a = jax.random.uniform(key, jnp.shape(xv), jnp.float32, lower, upper)
        return _rrelu_train(x, a.astype(xv.dtype))
    return _rrelu_p(x, lower=float(lower), upper=float(upper))


def maxout(x, groups, axis=1, name=None):
    xv = unwrap(x)
    shape = list(jnp.shape(xv))
    c = shape[axis]
    p = _maxout_prim(groups, axis)
    return p(x)


_maxout_cache = {}


def _maxout_prim(groups, axis):
    key = (groups, axis)
    if key not in _maxout_cache:
        def fn(x, _g=groups, _a=axis):
            shape = list(x.shape)
            c = shape[_a]
            new = shape[:_a] + [_g, c // _g] + shape[_a + 1:]
            return jnp.max(jnp.reshape(x, new), axis=_a)
        _maxout_cache[key] = Primitive(f"maxout[{groups},{axis}]", fn)
    return _maxout_cache[key]


def glu(x, axis=-1, name=None):
    return _glu_p(x, axis=int(axis))


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...ops import cast
        x = cast(x, dtype)
    return _softmax_p(x, axis=int(axis))


def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...ops import cast
        x = cast(x, dtype)
    return _log_softmax_p(x, axis=int(axis))


def _gumbel_fn(v, g, temperature=1.0, axis=-1, hard=False):
    y = jax.nn.softmax((v + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        hard_y = jnp.put_along_axis(jnp.zeros_like(y), idx,
                                    jnp.ones_like(idx, y.dtype), axis=axis,
                                    inplace=False)
        y = jax.lax.stop_gradient(hard_y - y) + y
    return y


_gumbel_p = Primitive("gumbel_softmax", _gumbel_fn)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import default_generator
    xv = unwrap(x)
    g = jax.random.gumbel(default_generator.next_key(), jnp.shape(xv),
                          jnp.float32).astype(xv.dtype)
    return _gumbel_p(x, g, temperature=float(temperature), axis=int(axis),
                     hard=bool(hard))
