"""Pooling functionals.

Reference parity: paddle/fluid/operators/pool_op.cc and
python/paddle/nn/functional/pooling.py. Lowered to lax.reduce_window (XLA
pooling primitive). Paddle's ``exclusive=True`` average (divide by the number
of valid elements, not window size) is implemented by reduce-window-summing a
ones mask.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.primitive import Primitive
from ...framework.tensor import Tensor, unwrap
from .conv import _norm_tuple, _norm_padding


def _window(nsp, channel_last, kernel, stride):
    if channel_last:
        return (1,) + kernel + (1,), (1,) + stride + (1,)
    return (1, 1) + kernel, (1, 1) + stride


def _pad_spec(pad, nsp, channel_last):
    if isinstance(pad, str):
        return pad
    if channel_last:
        return ((0, 0),) + tuple(pad) + ((0, 0),)
    return ((0, 0), (0, 0)) + tuple(pad)


def _max_pool_fn(x, kernel=(2, 2), stride=(2, 2), padding="VALID",
                 channel_last=False, nsp=2):
    win, strd = _window(nsp, channel_last, kernel, stride)
    pad = _pad_spec(padding, nsp, channel_last)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return jax.lax.reduce_window(x, init, jax.lax.max, win, strd, pad)


def _avg_pool_fn(x, kernel=(2, 2), stride=(2, 2), padding="VALID",
                 channel_last=False, nsp=2, exclusive=True):
    win, strd = _window(nsp, channel_last, kernel, stride)
    pad = _pad_spec(padding, nsp, channel_last)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, win, strd, pad)
    if exclusive and pad != "VALID":
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, win, strd, pad)
        return summed / counts
    return summed / float(np.prod(kernel))


_max_pool_p = Primitive("max_pool", _max_pool_fn)
_avg_pool_p = Primitive("avg_pool", _avg_pool_fn)


def _pool(kind, x, kernel_size, stride, padding, nsp, data_format, exclusive=True,
          ceil_mode=False):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    kernel = _norm_tuple(kernel_size, nsp)
    stride = _norm_tuple(stride if stride is not None else kernel_size, nsp)
    pad = _norm_padding(padding, nsp)
    if kind == "max":
        return _max_pool_p(x, kernel=kernel, stride=stride, padding=pad,
                           channel_last=channel_last, nsp=nsp)
    return _avg_pool_p(x, kernel=kernel, stride=stride, padding=pad,
                       channel_last=channel_last, nsp=nsp, exclusive=exclusive)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool("max", x, kernel_size, stride, padding, 1, df)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool("max", x, kernel_size, stride, padding, 2, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool("max", x, kernel_size, stride, padding, 3, data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool("avg", x, kernel_size, stride, padding, 1, df, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool("avg", x, kernel_size, stride, padding, 2, data_format,
                 exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool("avg", x, kernel_size, stride, padding, 3, data_format,
                 exclusive)


def _adaptive_pool_fn(x, out_size=(1, 1), kind="avg", channel_last=False,
                      nsp=2):
    spatial_axes = tuple(range(1, 1 + nsp)) if channel_last \
        else tuple(range(2, 2 + nsp))
    # adaptive pooling with uniform bins when divisible; general case uses
    # mean over index buckets
    for ax, osz in zip(spatial_axes, out_size):
        isz = x.shape[ax]
        if isz % osz == 0:
            k = isz // osz
            shape = list(x.shape)
            shape[ax] = osz
            shape.insert(ax + 1, k)
            x = jnp.reshape(x, shape)
            x = jnp.max(x, axis=ax + 1) if kind == "max" else jnp.mean(x, axis=ax + 1)
        else:
            # bucketed gather: start/end per output position (static python loop)
            segs = []
            for o in range(osz):
                s = (o * isz) // osz
                e = -(-((o + 1) * isz) // osz)
                sl = [slice(None)] * x.ndim
                sl[ax] = slice(s, e)
                seg = x[tuple(sl)]
                seg = jnp.max(seg, axis=ax, keepdims=True) if kind == "max" \
                    else jnp.mean(seg, axis=ax, keepdims=True)
                segs.append(seg)
            x = jnp.concatenate(segs, axis=ax)
    return x


_adaptive_p = Primitive("adaptive_pool", _adaptive_pool_fn)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_p(x, out_size=_norm_tuple(output_size, 1), kind="avg",
                       channel_last=False, nsp=1)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_p(x, out_size=_norm_tuple(output_size, 2), kind="avg",
                       channel_last=data_format == "NHWC", nsp=2)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_p(x, out_size=_norm_tuple(output_size, 3), kind="avg",
                       channel_last=data_format == "NDHWC", nsp=3)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_p(x, out_size=_norm_tuple(output_size, 1), kind="max",
                       channel_last=False, nsp=1)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_p(x, out_size=_norm_tuple(output_size, 2), kind="max",
                       channel_last=False, nsp=2)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_p(x, out_size=_norm_tuple(output_size, 3), kind="max",
                       channel_last=False, nsp=3)
