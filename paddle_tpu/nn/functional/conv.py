"""Convolution functionals.

Reference parity: paddle/fluid/operators/conv_op.cc, conv_transpose_op.cc and
python/paddle/nn/functional/conv.py. TPU-first: everything lowers to
lax.conv_general_dilated, which XLA tiles directly onto the MXU; the cuDNN
algorithm-search machinery of the reference (conv_cudnn_helper.h) has no
equivalent because XLA picks the layout/tiling.

Weight layout follows Paddle: OIHW (out, in/groups, kh, kw); data NCHW or NHWC.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.primitive import Primitive
from ...framework.tensor import Tensor, unwrap


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n):
    """Return lax padding spec: 'SAME'/'VALID' or [(lo,hi)]*n."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return tuple((int(padding), int(padding)) for _ in range(n))
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return tuple((int(p), int(p)) for p in padding)
    if len(padding) == 2 * n:
        return tuple((int(padding[2 * i]), int(padding[2 * i + 1]))
                     for i in range(n))
    # nested [[lo,hi],...]
    return tuple((int(p[0]), int(p[1])) for p in padding)


def _dims(ndim_spatial, channel_last):
    if ndim_spatial == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if ndim_spatial == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_fn(x, w, b=None, stride=(1, 1), padding="VALID", dilation=(1, 1),
             groups=1, channel_last=False, nsp=2):
    lhs_spec, rhs_spec, out_spec = _dims(nsp, channel_last)
    if channel_last:
        # paddle weights stay OIHW; transpose once for the NHWC conv form
        perm = tuple(range(2, 2 + nsp)) + (1, 0)
        w = jnp.transpose(w, perm)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        (lhs_spec, rhs_spec, out_spec))
    # NB: no preferred_element_type=f32 here — it makes the VJP's
    # transpose-rhs conv see (bf16 activations, f32 cotangent) and the
    # dtype rule rejects that; XLA:TPU already accumulates bf16 convs in
    # f32 on the MXU, so bf16-in/bf16-out loses nothing
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    out = out.astype(x.dtype)
    if b is not None:
        bshape = (1, -1) + (1,) * nsp if not channel_last else (1,) * (1 + nsp) + (-1,)
        out = out + jnp.reshape(b, bshape)
    return out


_conv_p = Primitive("conv2d", _conv_fn)


def _conv_impl(x, weight, bias, stride, padding, dilation, groups, data_format,
               nsp):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _norm_tuple(stride, nsp)
    dilation = _norm_tuple(dilation, nsp)
    pad = _norm_padding(padding, nsp)
    args = [x, weight] + ([bias] if bias is not None else [])
    if bias is not None:
        return _conv_p(x, weight, bias, stride=stride, padding=pad,
                       dilation=dilation, groups=int(groups),
                       channel_last=channel_last, nsp=nsp)
    return _conv_nb_p(x, weight, stride=stride, padding=pad, dilation=dilation,
                      groups=int(groups), channel_last=channel_last, nsp=nsp)


_conv_nb_p = Primitive("conv2d_nobias",
                       lambda x, w, **kw: _conv_fn(x, w, None, **kw))


def _conv_bn_act_fn(x, w, gamma, beta, rmean, rvar, momentum=0.9, eps=1e-5,
                    stride=1, padding=0, relu=True, s2d=False):
    """Fused NHWC conv+BN(+ReLU) through the Pallas pipeline
    (ops/pallas/fused_conv.py), with the batch_norm_train running-stat
    contract.  ``s2d=True`` applies the space-to-depth stem reorg (7×7/s2
    → 4×4/s1 over 12 channels) INSIDE the op so the reorged conv feeds
    the fused kernel directly — s2d at the XLA level alone was measured
    slower (PERF.md r3) and must not ship without the kernel."""
    from ...ops.pallas import fused_conv
    from .norm import _running_update
    if s2d:
        x = fused_conv.stem_s2d_input(x)
        w = fused_conv.stem_s2d_weight(w)
        stride, padding = 1, 0
    y, mean, var = fused_conv.fused_conv_bn_act(
        x, w, gamma.astype(jnp.float32), beta.astype(jnp.float32),
        int(stride), int(padding), float(eps), bool(relu))
    new_rmean, new_rvar = _running_update(rmean, rvar, mean, var, momentum)
    return y, new_rmean, new_rvar


_conv_bn_act_p = Primitive("conv2d_bn_act", _conv_bn_act_fn,
                           multi_output=True)


def conv_bn_fusable(x, weight, stride, padding, dilation, groups,
                    data_format, s2d=False):
    """One cheap static check deciding the fused-vs-XLA branch (the
    off-path must stay one branch — ISSUE 2 acceptance)."""
    from ...framework import core
    from ...framework.tensor import Tensor
    from ...ops.pallas import fused_conv
    if not fused_conv.enabled() or core.in_static_mode():
        return False
    xv, wv = unwrap(x), unwrap(weight)
    if s2d:
        return fused_conv.stem_supported(tuple(xv.shape), tuple(wv.shape))
    return fused_conv.supports(
        tuple(xv.shape), tuple(wv.shape), stride, padding, dilation, groups,
        channel_last=data_format in ("NHWC",))


def conv_bn_act(x, weight, gamma, beta, running_mean, running_var,
                momentum=0.9, epsilon=1e-5, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", act=None, training=True,
                s2d=False, name=None):
    """conv2d → batch_norm → activation, fused through the Pallas
    conv+BN+ReLU pipeline when ``FLAGS_use_pallas_fused_conv`` is on and
    the site is eligible; otherwise the exact XLA composition (reference:
    operators/fused/conv_fusion_op.cc).  Running stats update with the
    shared momentum convention either way."""
    relu = act == "relu"
    if training and act in (None, "relu") and conv_bn_fusable(
            x, weight, stride, padding, dilation, groups, data_format, s2d):
        def _i(v):
            return int(v[0]) if isinstance(v, (tuple, list)) else int(v)
        out, nm, nv = _conv_bn_act_p(
            x, weight, gamma, beta, running_mean, running_var,
            momentum=float(momentum), eps=float(epsilon), stride=_i(stride),
            padding=_i(padding), relu=relu, s2d=bool(s2d))
        # functional-state write-back, same as F.batch_norm's train path
        if isinstance(running_mean, Tensor) and isinstance(nm, Tensor):
            running_mean.set_value(nm._value)
            running_var.set_value(nv._value)
        return out
    from .norm import batch_norm
    y = conv2d(x, weight, None, stride, padding, dilation, groups,
               data_format)
    y = batch_norm(y, running_mean, running_var, gamma, beta,
                   training=training, momentum=momentum, epsilon=epsilon,
                   data_format=data_format)
    if act is not None:
        from . import activation as A
        y = getattr(A, act)(y)
    return y


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC",) else "NCW"
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups, df, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups,
                      data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups,
                      data_format, 3)


def _conv_transpose_fn(x, w, b=None, stride=(1, 1), padding=(0, 0),
                       output_padding=(0, 0), dilation=(1, 1), groups=1,
                       channel_last=False, nsp=2):
    lhs_spec, rhs_spec, out_spec = _dims(nsp, channel_last)
    if channel_last:
        perm = tuple(range(2, 2 + nsp)) + (1, 0)
        wt = jnp.transpose(w, perm)  # spatial..., I, O with paddle w = (in, out/g, k)
        wt = jnp.swapaxes(wt, -1, -2)
    else:
        # paddle conv_transpose weight layout: (in, out/groups, kh, kw) = IOHW
        wt = jnp.swapaxes(w, 0, 1)  # -> (out/g, in, kh, kw)
        if groups > 1:
            # regroup: (g*out_g, in_g, ...) expected by transposed conv below
            pass
    # implement via gradient of forward conv: conv_transpose == lhs-dilated conv
    pads = tuple((d * (k - 1) - p[0], d * (k - 1) - p[1] + op)
                 for p, op, k, d in zip(padding, output_padding,
                                        wt.shape[2:2 + nsp] if not channel_last
                                        else wt.shape[:nsp], dilation))
    if channel_last:
        wt2 = jnp.flip(wt, axis=tuple(range(nsp)))
        dn = jax.lax.conv_dimension_numbers(
            x.shape, wt2.shape, (lhs_spec, rhs_spec, out_spec))
        out = jax.lax.conv_general_dilated(
            x, wt2, window_strides=(1,) * nsp, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
    else:
        wt2 = jnp.flip(wt, axis=tuple(range(2, 2 + nsp)))
        if groups > 1:
            # (out/g, in, k): split input-channel dim across groups
            o_g, i_all = wt2.shape[0], wt2.shape[1]
            wt2 = jnp.reshape(wt2, (o_g, groups, i_all // groups) + wt2.shape[2:])
            wt2 = jnp.transpose(wt2, (1, 0) + tuple(range(2, wt2.ndim)))
            wt2 = jnp.reshape(wt2, (groups * o_g,) + wt2.shape[2:])
        dn = jax.lax.conv_dimension_numbers(
            x.shape, wt2.shape, (lhs_spec, rhs_spec, out_spec))
        out = jax.lax.conv_general_dilated(
            x, wt2, window_strides=(1,) * nsp, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
    out = out.astype(x.dtype)
    if b is not None:
        bshape = (1, -1) + (1,) * nsp if not channel_last else (1,) * (1 + nsp) + (-1,)
        out = out + jnp.reshape(b, bshape)
    return out


_convt_p = Primitive("conv2d_transpose", _conv_transpose_fn)
_convt_nb_p = Primitive("conv2d_transpose_nobias",
                        lambda x, w, **kw: _conv_transpose_fn(x, w, None, **kw))


def _conv_transpose_impl(x, weight, bias, stride, padding, output_padding,
                         dilation, groups, data_format, nsp):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _norm_tuple(stride, nsp)
    dilation = _norm_tuple(dilation, nsp)
    output_padding = _norm_tuple(output_padding, nsp)
    pad = _norm_padding(padding, nsp)
    if isinstance(pad, str):
        if pad == "VALID":
            pad = tuple((0, 0) for _ in range(nsp))
        else:
            raise ValueError("SAME padding unsupported for conv_transpose; "
                             "give explicit pads (paddle parity)")
    kw = dict(stride=stride, padding=pad, output_padding=output_padding,
              dilation=dilation, groups=int(groups),
              channel_last=channel_last, nsp=nsp)
    if bias is not None:
        return _convt_p(x, weight, bias, **kw)
    return _convt_nb_p(x, weight, **kw)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose_impl(x, weight, bias, stride, padding,
                                output_padding, dilation, groups, df, 1)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", name=None):
    return _conv_transpose_impl(x, weight, bias, stride, padding,
                                output_padding, dilation, groups,
                                data_format, 2)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW", name=None):
    return _conv_transpose_impl(x, weight, bias, stride, padding,
                                output_padding, dilation, groups,
                                data_format, 3)
