"""Common functionals: linear, dropout, embedding, interpolate, etc.

Reference parity: python/paddle/nn/functional/common.py + input.py
(embedding/one_hot), mul_op/matmul for linear, dropout_op.cc,
lookup_table_v2_op.cc (embedding; SelectedRows sparse grad becomes XLA
scatter-add through the take VJP -- idiomatic TPU replacement),
interpolate_op.cc, pixel_shuffle_op.cc, unfold_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.primitive import Primitive
from ...framework.random import default_generator
from ...framework.tensor import Tensor, unwrap
from ...ops.manipulation import pad as _pad_op  # re-export surface

pad = _pad_op

_linear_b = Primitive(
    "linear",
    lambda x, w, b: (jnp.matmul(
        x, w, preferred_element_type=jnp.float32
        if jnp.result_type(x, w) == jnp.bfloat16 else None)
        .astype(jnp.result_type(x, w)) + b))
_linear_nb = Primitive(
    "linear_nobias",
    lambda x, w: jnp.matmul(
        x, w, preferred_element_type=jnp.float32
        if jnp.result_type(x, w) == jnp.bfloat16 else None)
    .astype(jnp.result_type(x, w)))


def linear(x, weight, bias=None, name=None):
    """paddle weight layout: [in_features, out_features]."""
    if bias is not None:
        return _linear_b(x, weight, bias)
    return _linear_nb(x, weight)


def _dropout_fn(x, key, p=0.5, mode="upscale_in_train", axis=None):
    from ...framework.random import ensure_key
    key = ensure_key(key)      # static programs carry raw int32 key data
    if p == 0.0:
        return x
    if axis is None:
        shape = x.shape
    else:
        shape = tuple(x.shape[i] if i in axis else 1 for i in range(x.ndim))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype)).astype(x.dtype)
    return jnp.where(mask, x, jnp.zeros((), x.dtype)).astype(x.dtype)


_dropout_p = Primitive("dropout", _dropout_fn)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            from ...ops.math import scale as _scale
            return _scale(x, scale=1.0 - p)
        return x
    from ...framework import core as _core
    if _core.in_static_mode():
        # a plain next_key() would bake into the Program as a constant —
        # identical masks on every run and every scanned step
        from ...framework.random import static_advancing_key
        key = static_advancing_key("dropout")
    else:
        key = default_generator.next_key()
    ax = tuple(int(a) for a in axis) if axis is not None else None
    if isinstance(ax, tuple) and len(ax) == 0:
        ax = None
    return _dropout_p(x, key, p=float(p), mode=mode, axis=ax)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale_ = 1.0507009873554805
    alpha_p = -alpha * scale_
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    key = default_generator.next_key()
    p_prim = _alpha_dropout_p
    return p_prim(x, key, p=float(p), a=float(a), b=float(b),
                  alpha_p=float(alpha_p))


def _alpha_dropout_fn(x, key, p=0.5, a=1.0, b=0.0, alpha_p=-1.7580993408473766):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    return (a * jnp.where(mask, x, jnp.asarray(alpha_p, x.dtype)) + b).astype(x.dtype)


_alpha_dropout_p = Primitive("alpha_dropout", _alpha_dropout_fn)

_embedding_p = Primitive("lookup_table_v2",
                         lambda w, ids, padding_idx=None:
                         _embedding_fn(w, ids, padding_idx))


def _embedding_fn(w, ids, padding_idx):
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = jnp.where(mask, out, jnp.zeros((), out.dtype))
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """lookup_table_v2 parity.  With ``sparse=True`` in eager mode the
    weight gradient is a SelectedRows (rows = the looked-up ids) instead of
    a dense vocab-sized buffer — the reference's is_sparse grad path
    (lookup_table_v2_op.cc); sparse optimizers then update only those rows.
    Inside traced/static code the dense scatter-add path is used (XLA has no
    sparse tensors)."""
    pi = None if padding_idx is None else int(padding_idx)
    if pi is not None and pi < 0:
        pi = int(unwrap(weight).shape[0]) + pi
    if sparse:
        import jax as _jax
        from ...framework import core as _core
        from ...framework.tensor import Tensor as _T
        concrete = isinstance(weight, _T) and \
            not isinstance(unwrap(weight), _jax.core.Tracer)
        if not _core.in_static_mode() and concrete:
            from ...framework.selected_rows import sparse_lookup
            return sparse_lookup(weight, x, padding_idx=pi)
    return _embedding_p(weight, x, padding_idx=pi)


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh
    return _oh(x, num_classes)


def _interp_fn(x, size=(2, 2), mode="nearest", align_corners=False,
               channel_last=False):
    # NCHW -> resize spatial dims
    if channel_last:
        spatial_start = 1
    else:
        spatial_start = 2
    nsp = len(size)
    new_shape = list(x.shape)
    for i, s in enumerate(size):
        new_shape[spatial_start + i] = s
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    if align_corners and method != "nearest":
        # jax.image.resize has no align_corners; emulate with explicit grid
        idx = []
        for i, s in enumerate(size):
            isz = x.shape[spatial_start + i]
            pos = jnp.linspace(0, isz - 1, s)
            idx.append(pos)
        out = x
        for i, pos in enumerate(idx):
            ax = spatial_start + i
            lo = jnp.floor(pos).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, x.shape[ax] - 1)
            w = (pos - lo).astype(x.dtype)
            lo_v = jnp.take(out, lo, axis=ax)
            hi_v = jnp.take(out, hi, axis=ax)
            bshape = [1] * out.ndim
            bshape[ax] = -1
            w = w.reshape(bshape)
            out = lo_v * (1 - w) + hi_v * w
        return out
    return jax.image.resize(x, tuple(new_shape), method=method).astype(x.dtype)


_interp_p = Primitive("interpolate", _interp_fn)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    nd = x.ndim if isinstance(x, Tensor) else jnp.ndim(unwrap(x))
    nsp = nd - 2
    shape = x.shape if isinstance(x, Tensor) else list(jnp.shape(unwrap(x)))
    spatial = shape[1:1 + nsp] if channel_last else shape[2:2 + nsp]
    if size is not None:
        if isinstance(size, (int, np.integer)):
            size = [int(size)] * nsp
        size = tuple(int(unwrap(s)) for s in size)
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * nsp
        size = tuple(int(s * f) for s, f in zip(spatial, scale_factor))
    return _interp_p(x, size=size, mode=mode, align_corners=bool(align_corners),
                     channel_last=channel_last)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def _pixel_shuffle_fn(x, factor=2):
    n, c, h, w = x.shape
    r = factor
    x = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return jnp.reshape(x, (n, c // (r * r), h * r, w * r))


_pixel_shuffle_p = Primitive("pixel_shuffle", _pixel_shuffle_fn)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _pixel_shuffle_p(x, factor=int(upscale_factor))


def _unfold_fn(x, k=(3, 3), stride=(1, 1), padding=(0, 0), dilation=(1, 1)):
    n, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (padding[0], padding[0]),
                    (padding[1], padding[1])))
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=stride, padding="VALID",
        rhs_dilation=dilation, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # -> (N, C*kh*kw, oh, ow) -> (N, C*kh*kw, L)
    return jnp.reshape(patches, (n, patches.shape[1], -1))


_unfold_p = Primitive("unfold", _unfold_fn)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _norm_tuple
    return _unfold_p(x, k=_norm_tuple(kernel_sizes, 2),
                     stride=_norm_tuple(strides, 2),
                     padding=_norm_tuple(paddings, 2),
                     dilation=_norm_tuple(dilations, 2))


_cos_sim = Primitive("cosine_similarity",
                     lambda x1, x2, axis=1, eps=1e-8:
                     jnp.sum(x1 * x2, axis=axis) /
                     jnp.maximum(jnp.linalg.norm(x1, axis=axis) *
                                 jnp.linalg.norm(x2, axis=axis), eps))


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return _cos_sim(x1, x2, axis=int(axis), eps=float(eps))


_bilinear_p = Primitive(
    "bilinear",
    lambda x1, x2, w, b=None: _bilinear_fn(x1, x2, w, b))


def _bilinear_fn(x1, x2, w, b):
    # w: (out, in1, in2)
    out = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
    if b is not None:
        out = out + b
    return out


def bilinear(x1, x2, weight, bias=None, name=None):
    if bias is not None:
        return _bilinear_p(x1, x2, weight, bias)
    return _bilinear_nb(x1, x2, weight)


_bilinear_nb = Primitive("bilinear_nobias",
                         lambda x1, x2, w: _bilinear_fn(x1, x2, w, None))

_label_smooth_p = Primitive(
    "label_smooth",
    lambda label, epsilon=0.1: (1 - epsilon) * label + epsilon / label.shape[-1])


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        p = _label_smooth_prior
        return p(label, prior_dist, epsilon=float(epsilon))
    return _label_smooth_p(label, epsilon=float(epsilon))


_label_smooth_prior = Primitive(
    "label_smooth_prior",
    lambda label, prior, epsilon=0.1: (1 - epsilon) * label + epsilon * prior)


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (class_center_sample_op.cu): keep
    every positive class present in the batch, fill up to ``num_samples``
    with uniformly drawn negatives, and remap labels into the sampled
    index space. Host-side numpy by design — the output SIZE is
    data-dependent (XLA-hostile) and the op is a data-prep step feeding
    the sharded-FC matmul, not the hot path."""
    lab = np.asarray(unwrap(label)).ravel()
    pos = np.unique(lab)
    if pos.size >= num_samples:
        sampled = np.sort(pos)
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes, dtype=lab.dtype),
                                pos, assume_unique=True)
        # draw through the framework generator: reproducible under
        # paddle.seed AND advancing per call, so each step resamples fresh
        # negatives (PartialFC resamples per batch)
        key = default_generator.next_key()
        seed32 = int(np.asarray(
            jax.random.randint(key, (), 0, 2 ** 31 - 1)))
        rng = np.random.RandomState(seed32)
        chosen = rng.choice(neg_pool, size=num_samples - pos.size,
                            replace=False)
        sampled = np.sort(np.concatenate([pos, chosen]))
    remapped = np.searchsorted(sampled, lab)
    return (Tensor(jnp.asarray(remapped.astype(np.int64))),
            Tensor(jnp.asarray(sampled.astype(np.int64))))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    xv = unwrap(x)
    if maxlen is None:
        maxlen = int(np.asarray(xv).max())
    from ...framework.dtype import convert_dtype
    rng = jnp.arange(maxlen)
    return Tensor((rng[None, :] < xv[:, None]).astype(convert_dtype(dtype)))
