"""Normalization functionals.

Reference parity: batch_norm_op.cc, layer_norm_op.cc, instance_norm_op.cc,
group_norm_op.cc and python/paddle/nn/functional/norm.py. TPU-first: all are
single fused reduction+scale expressions; batch_norm in training mode returns
(out, new_mean, new_var) functionally -- the Layer writes the running stats
back (and paddle_tpu.jit captures those writes when tracing).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.primitive import Primitive
from ...framework.tensor import Tensor, unwrap


def _bn_axes(ndim, data_format):
    ch = 1 if data_format.startswith("NC") else ndim - 1
    reduce_axes = tuple(i for i in range(ndim) if i != ch)
    return ch, reduce_axes


def _running_update(rmean, rvar, mean, var, momentum):
    """THE running-stat convention (one source of truth for every BN
    path): momentum·old + (1−momentum)·batch-stat."""
    new_rmean = momentum * rmean + (1 - momentum) * mean.astype(rmean.dtype)
    new_rvar = momentum * rvar + (1 - momentum) * var.astype(rvar.dtype)
    return new_rmean, new_rvar


def _bn_apply(x, xf, gamma, beta, rmean, rvar, mean, var, momentum, eps,
              ch):
    """Shared normalize+affine+running-update tail of the train-mode BN
    primitives (the only difference between plain and sync BN is where
    mean/var came from)."""
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    inv = jax.lax.rsqrt(var + eps)
    out = (xf - mean.reshape(shape)) * inv.reshape(shape)
    out = out * gamma.astype(jnp.float32).reshape(shape) + \
        beta.astype(jnp.float32).reshape(shape)
    new_rmean, new_rvar = _running_update(rmean, rvar, mean, var, momentum)
    return out.astype(x.dtype), new_rmean, new_rvar


def _bn_train_fn(x, gamma, beta, rmean, rvar, momentum=0.9, eps=1e-5,
                 data_format="NCHW"):
    ch, axes = _bn_axes(x.ndim, data_format)
    if ch == x.ndim - 1:
        # channels-last: the fused Pallas epilogue applies when opted in
        # (measured parity with XLA on the bench chip — see
        # ops/pallas/fused_bn.py's gating note)
        from ...ops.pallas import fused_bn
        if (fused_bn.enabled() and (x.size // x.shape[-1]) % 8 == 0
                and jax.device_count() == 1):
            # single-device only: pallas_call has no GSPMD partition rule,
            # so under multi-device pjit it would replicate the activation
            # (and under shard_map compute per-shard moments)
            x2d = x.reshape(-1, x.shape[-1])
            y, mean, var = fused_bn.fused_bn_act(
                x2d, gamma.astype(jnp.float32), beta.astype(jnp.float32),
                float(eps), False)
            new_rmean, new_rvar = _running_update(rmean, rvar, mean, var,
                                                  momentum)
            return y.reshape(x.shape), new_rmean, new_rvar
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    return _bn_apply(x, xf, gamma, beta, rmean, rvar, mean, var, momentum,
                     eps, ch)


def _bn_eval_fn(x, gamma, beta, rmean, rvar, eps=1e-5, data_format="NCHW"):
    ch, _ = _bn_axes(x.ndim, data_format)
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(rvar.astype(jnp.float32) + eps)
    out = (xf - rmean.astype(jnp.float32).reshape(shape)) * inv.reshape(shape)
    out = out * gamma.astype(jnp.float32).reshape(shape) + \
        beta.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype)


def _sync_bn_train_fn(x, gamma, beta, rmean, rvar, momentum=0.9, eps=1e-5,
                      data_format="NCHW"):
    """sync_batch_norm_op.cu parity: batch statistics are GLOBAL across
    the dp replicas.  Under GSPMD (pjit whole-array semantics) the plain
    mean already reduces over the logical global batch, so this equals
    _bn_train_fn; under a MANUAL dp axis (shard_map) the local moments
    are explicitly pmean'd — the reference's ncclAllReduce of
    sum/sum-of-squares."""
    ch, axes = _bn_axes(x.ndim, data_format)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    meansq = jnp.mean(xf * xf, axis=axes)
    from ...distributed.collective import _axis_bound
    from ...parallel.mesh import DP_AXIS
    if _axis_bound(DP_AXIS):
        mean = jax.lax.pmean(mean, DP_AXIS)
        meansq = jax.lax.pmean(meansq, DP_AXIS)
    # E[x²]−E[x]² cancels catastrophically in fp32 for large-offset data
    # (negative "variance" → NaN rsqrt); clamp AFTER the pmean so the
    # cross-replica combination stays exact
    var = jnp.maximum(meansq - mean * mean, 0.0)
    return _bn_apply(x, xf, gamma, beta, rmean, rvar, mean, var, momentum,
                     eps, ch)


_bn_train = Primitive("batch_norm_train", _bn_train_fn, multi_output=True)
_bn_eval = Primitive("batch_norm_eval", _bn_eval_fn)
_sync_bn_train = Primitive("sync_batch_norm_train", _sync_bn_train_fn,
                           multi_output=True)


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, sync=False, name=None):
    if use_global_stats:
        training = False
    if training:
        prim = _sync_bn_train if sync else _bn_train
        out, nm, nv = prim(x, weight, bias, running_mean, running_var,
                           momentum=float(momentum), eps=float(epsilon),
                           data_format=data_format)
        # functional-state write-back: Layer buffers mutate eagerly; jit
        # tracing captures the set_value (see jit/state tracking).
        if isinstance(running_mean, Tensor) and isinstance(nm, Tensor):
            running_mean.set_value(nm._value)
            running_var.set_value(nv._value)
        elif not isinstance(nm, Tensor):
            # static-graph recording: alias the op's stat outputs to the
            # persistable running-stat NAMES so the executor's persistable
            # write-back updates them (the reference's in-place
            # MeanOut/VarianceOut of batch_norm_op.cc)
            mname = getattr(running_mean, "name", None)
            vname = getattr(running_var, "name", None)
            bn_op = getattr(nm, "op", None)       # the recording Operator
            if mname and vname and bn_op is not None:
                bn_op.output_names[1] = mname
                bn_op.output_names[2] = vname
        return out
    return _bn_eval(x, weight, bias, running_mean, running_var,
                    eps=float(epsilon), data_format=data_format)


def _ln_fn(x, gamma=None, beta=None, eps=1e-5, begin_axis=-1):
    axes = tuple(range(begin_axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    if gamma is not None:
        out = out * gamma.astype(jnp.float32)
    if beta is not None:
        out = out + beta.astype(jnp.float32)
    return out.astype(x.dtype)


_ln = Primitive("layer_norm", _ln_fn)
_ln_nogb = Primitive("layer_norm_nogb",
                     lambda x, eps=1e-5, begin_axis=-1:
                     _ln_fn(x, None, None, eps, begin_axis))


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        n_axes = 1
    else:
        n_axes = len(list(normalized_shape))
    begin = (x.ndim if isinstance(x, Tensor) else jnp.ndim(unwrap(x))) - n_axes
    if weight is not None and bias is not None:
        return _ln(x, weight, bias, eps=float(epsilon), begin_axis=begin)
    if weight is None and bias is None:
        return _ln_nogb(x, eps=float(epsilon), begin_axis=begin)
    # one of the two
    from ...ops import zeros, ones
    if weight is None:
        shape = [unwrap(x).shape[i] for i in range(begin, unwrap(x).ndim)]
        weight = ones(shape, dtype=str(unwrap(x).dtype))
    if bias is None:
        shape = [unwrap(x).shape[i] for i in range(begin, unwrap(x).ndim)]
        bias = zeros(shape, dtype=str(unwrap(x).dtype))
    return _ln(x, weight, bias, eps=float(epsilon), begin_axis=begin)


def _in_fn(x, gamma=None, beta=None, eps=1e-5):
    # instance norm over spatial dims, per (N, C)
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    if gamma is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out * gamma.astype(jnp.float32).reshape(shape)
        out = out + beta.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype)


_in_p = Primitive("instance_norm", _in_fn)
_in_nogb = Primitive("instance_norm_nogb",
                     lambda x, eps=1e-5: _in_fn(x, None, None, eps))


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    if weight is not None:
        return _in_p(x, weight, bias, eps=float(eps))
    return _in_nogb(x, eps=float(eps))


def _gn_fn(x, gamma=None, beta=None, groups=1, eps=1e-5):
    n, c = x.shape[0], x.shape[1]
    xf = x.astype(jnp.float32)
    grouped = jnp.reshape(xf, (n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    out = (grouped - mean) * jax.lax.rsqrt(var + eps)
    out = jnp.reshape(out, x.shape)
    if gamma is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out * gamma.astype(jnp.float32).reshape(shape)
        out = out + beta.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype)


_gn_p = Primitive("group_norm", _gn_fn)
_gn_nogb = Primitive("group_norm_nogb",
                     lambda x, groups=1, eps=1e-5: _gn_fn(x, None, None,
                                                          groups, eps))


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    if weight is not None:
        return _gn_p(x, weight, bias, groups=int(num_groups),
                     eps=float(epsilon))
    return _gn_nogb(x, groups=int(num_groups), eps=float(epsilon))


def _l2norm_fn(x, axis=1, eps=1e-12, p=2.0):
    norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, eps)


_l2norm = Primitive("normalize", _l2norm_fn)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _l2norm(x, axis=int(axis), eps=float(epsilon), p=float(p))


def _lrn_fn(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    # local response norm across channels (NCHW)
    sq = jnp.square(x)
    half = size // 2
    pads = [(0, 0)] * x.ndim
    pads[1] = (half, size - 1 - half)
    sq = jnp.pad(sq, pads)
    win = [1] * x.ndim
    win[1] = size
    acc = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(win),
                                (1,) * x.ndim, "VALID")
    return x / jnp.power(k + alpha * acc, beta)


_lrn = Primitive("local_response_norm", _lrn_fn)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _lrn(x, size=int(size), alpha=float(alpha), beta=float(beta),
                k=float(k))
