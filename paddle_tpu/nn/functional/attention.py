"""Attention functionals.

Reference parity: the reference era predates fused attention ops (it has only
softmax/matmul composition inside nn/layer/transformer.py); we expose a
first-class ``scaled_dot_product_attention`` because it is THE hot op on TPU.
Default path is a single fused XLA expression (bf16 matmuls on the MXU with
f32 softmax accumulation); when FLAGS_use_pallas_kernels is set and we're on
TPU, the Pallas flash-attention kernel (paddle_tpu/ops/pallas/) takes over.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.flags import flag
from ...framework.primitive import Primitive
from ...framework.tensor import Tensor, unwrap


def _sdpa_fn(q, k, v, scale=None, causal=False):
    # q,k,v: (B, N, S, H) -- batch, heads, seq, head_dim
    hd = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bnsh,bnth->bnst", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,bnth->bnsh", probs, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _sdpa_mask_fn(q, k, v, mask, scale=None, causal=False):
    hd = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bnsh,bnth->bnst", q, k,
                        preferred_element_type=jnp.float32) * s
    logits = logits + mask.astype(logits.dtype)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,bnth->bnsh", probs, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


_sdpa = Primitive("scaled_dot_product_attention", _sdpa_fn)
_sdpa_mask = Primitive("scaled_dot_product_attention_mask", _sdpa_mask_fn)


def _use_pallas(q, k, mask=None, causal=False):
    if not flag("use_pallas_kernels"):
        return False
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    if platform not in ("tpu", "axon"):
        return False
    # the flash kernel's bias input is non-differentiable; a trainable mask
    # (learned relative-position bias) must take the XLA path
    if isinstance(mask, Tensor) and not mask.stop_gradient:
        return False
    from ...ops.pallas import supports
    from ...ops.pallas.flash_attention import MIN_SEQ_FOR_FLASH
    kshape = unwrap(k).shape
    # short sequences are dispatch/bandwidth-bound: the one-expression XLA
    # path wins there (measured crossover at Sk=1024 on v5e)
    if len(kshape) != 4 or kshape[-2] < MIN_SEQ_FOR_FLASH:
        return False
    mk = unwrap(mask).shape if mask is not None else None
    return supports(unwrap(q).shape, kshape, mk, causal=causal)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Inputs (B, S, N, H) per paddle-incubate convention; internally uses
    (B, N, S, H)."""
    from ...ops import transpose
    q = transpose(query, [0, 2, 1, 3])
    k = transpose(key, [0, 2, 1, 3])
    v = transpose(value, [0, 2, 1, 3])
    if _use_pallas(q, k, attn_mask, causal=bool(is_causal)):
        from ...ops.pallas import flash_attention
        out = flash_attention(q, k, v, bias=attn_mask, causal=is_causal)
    elif attn_mask is not None:
        out = _sdpa_mask(q, k, v, attn_mask, causal=bool(is_causal))
    else:
        out = _sdpa(q, k, v, causal=bool(is_causal))
    if dropout_p and training:
        from .common import dropout
        out = dropout(out, dropout_p, training=training)
    return transpose(out, [0, 2, 1, 3])


def _use_flash_decode(q, k, window):
    """Dispatch gate for the decode step: FLAGS_use_flash_decode + TPU
    platform + single-query shapes + a contiguous [start, end) validity
    window (the kernel masks a window, not an arbitrary dense mask)."""
    if window is None or not flag("use_flash_decode"):
        return False
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    if platform not in ("tpu", "axon"):
        return False
    from ...ops.pallas.flash_decode import supports_decode
    return supports_decode(unwrap(q).shape, unwrap(k).shape)


def cached_attention(q, k, v, attn_mask=None, window=None, k_scale=None,
                     v_scale=None):
    """Incremental attention: (B, N, Tq, H) new-token queries over the
    full (B, N, S, H) KV ring cache.

    ``attn_mask`` is the additive validity+causality mask the caller
    built from cache_position / per-row start offsets.  ``window`` is the
    optional ``(start[B], end[B])`` contiguous form of the same validity
    (decode steps: Tq == 1) — when present and eligible, the Pallas
    flash-decoding kernel (split-K over the cached context) takes over;
    otherwise the one-expression XLA masked attention runs.

    With ``k_scale``/``v_scale`` given (FLAGS_kv_cache_dtype=int8), k/v
    are int8 row planes and the scales are the per-(token, head) f32
    planes: the eligible kernel path fuses the dequant into its split-K
    loop (flash_decode_quant); the XLA fallback dequantizes the cache
    then attends (decode is inference-only, so the raw read costs no
    tape).
    """
    if k_scale is not None:
        if _use_flash_decode(q, k, window):
            from ...ops.pallas import flash_decode_quant
            return flash_decode_quant(q, k, v, k_scale, v_scale,
                                      window[0], window[1])
        from ..layer.transformer import dequantize_kv_rows
        dt = unwrap(q).dtype
        k = Tensor(dequantize_kv_rows(k, k_scale, dtype=dt))
        v = Tensor(dequantize_kv_rows(v, v_scale, dtype=dt))
    if _use_flash_decode(q, k, window):
        from ...ops.pallas import flash_decode
        return flash_decode(q, k, v, window[0], window[1])
    if attn_mask is not None:
        return _sdpa_mask(q, k, v, attn_mask)
    return _sdpa(q, k, v)


def attention_bnsh(q, k, v, attn_mask=None, is_causal=False):
    """(B, N, S, H) layout fast path used by our MultiHeadAttention layer."""
    if _use_pallas(q, k, attn_mask, causal=bool(is_causal)):
        from ...ops.pallas import flash_attention
        return flash_attention(q, k, v, bias=attn_mask, causal=is_causal)
    if attn_mask is not None:
        return _sdpa_mask(q, k, v, attn_mask, causal=bool(is_causal))
    return _sdpa(q, k, v, causal=bool(is_causal))
